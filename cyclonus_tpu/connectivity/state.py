"""TestCaseState: the mirror of the cluster during a test — every action is
dual-written to the in-memory model AND the cluster
(reference: connectivity/testcasestate.go)."""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List

from ..kube.ikubernetes import (
    IKubernetes,
    KubeError,
    delete_all_network_policies_in_namespaces,
    get_network_policies_in_namespaces,
    get_pods_in_namespaces,
)
from ..kube.netpol import NetworkPolicy
from ..kube.objects import KubeNamespace
from ..probe.resources import Resources


@dataclass
class LabelsDiff:
    """testcasestate.go:251-289."""

    same: List[str] = field(default_factory=list)
    different: List[str] = field(default_factory=list)
    extra: List[str] = field(default_factory=list)
    missing: List[str] = field(default_factory=list)

    @staticmethod
    def compare(actual: Dict[str, str], expected: Dict[str, str]) -> "LabelsDiff":
        ld = LabelsDiff()
        for k, actual_value in actual.items():
            if k not in expected:
                ld.extra.append(k)
            elif actual_value != expected[k]:
                ld.different.append(k)
            else:
                ld.same.append(k)
        for k in expected:
            if k not in actual:
                ld.missing.append(k)
        return ld

    def are_labels_equal(self) -> bool:
        return not self.different and not self.extra and not self.missing

    def are_all_expected_labels_present(self) -> bool:
        return not self.different and not self.missing


class TestCaseState:
    __test__ = False  # not a pytest class

    def __init__(
        self,
        kubernetes: IKubernetes,
        resources: Resources,
        policies: List[NetworkPolicy] = None,
        pod_wait_timeout_seconds: int = 60,
        pod_wait_sleep_seconds: int = 5,
    ):
        self.kubernetes = kubernetes
        self.resources = resources
        self.policies: List[NetworkPolicy] = list(policies or [])
        self.pod_wait_timeout_seconds = pod_wait_timeout_seconds
        self.pod_wait_sleep_seconds = pod_wait_sleep_seconds

    # --- policies ---

    def create_policy(self, policy: NetworkPolicy) -> None:
        for kube_pol in self.policies:
            if (
                kube_pol.namespace == policy.namespace
                and kube_pol.name == policy.name
            ):
                raise KubeError(
                    f"cannot create policy {policy.namespace}/{policy.name}: "
                    f"already exists"
                )
        self.policies.append(policy)
        self.kubernetes.create_network_policy(policy)

    def update_policy(self, policy: NetworkPolicy) -> None:
        for i, kube_pol in enumerate(self.policies):
            if (
                kube_pol.namespace == policy.namespace
                and kube_pol.name == policy.name
            ):
                self.policies[i] = policy
                self.kubernetes.update_network_policy(policy)
                return
        raise KubeError(
            f"cannot update policy {policy.namespace}/{policy.name}: not found"
        )

    def delete_policy(self, ns: str, name: str) -> None:
        index = -1
        for i, kube_pol in enumerate(self.policies):
            if kube_pol.namespace == ns and kube_pol.name == name:
                index = i
        if index == -1:
            raise KubeError(f"cannot delete policy {ns}/{name}: not found")
        self.policies = [p for i, p in enumerate(self.policies) if i != index]
        self.kubernetes.delete_network_policy(ns, name)

    def read_policies(self, namespaces: List[str]) -> None:
        self.policies.extend(
            get_network_policies_in_namespaces(self.kubernetes, namespaces)
        )

    # --- namespaces ---

    def create_namespace(self, ns: str, labels: Dict[str, str]) -> None:
        self.resources = self.resources.create_namespace(ns, labels)
        self.kubernetes.create_namespace(KubeNamespace(name=ns, labels=dict(labels)))

    def set_namespace_labels(self, ns: str, labels: Dict[str, str]) -> None:
        self.resources = self.resources.update_namespace_labels(ns, labels)
        self.kubernetes.set_namespace_labels(ns, labels)

    def delete_namespace(self, ns: str) -> None:
        self.resources = self.resources.delete_namespace(ns)
        self.kubernetes.delete_namespace(ns)

    # --- pods ---

    def create_pod(self, ns: str, pod: str, labels: Dict[str, str]) -> None:
        """Dual-create then wait-for-IP loop (testcasestate.go:81-112)."""
        self.resources = self.resources.create_pod(ns, pod, labels)
        new_pod = self.resources.get_pod(ns, pod)
        self.kubernetes.create_pod(new_pod.kube_pod())
        self.kubernetes.create_service(new_pod.kube_service())
        deadline = max(1, self.pod_wait_timeout_seconds // self.pod_wait_sleep_seconds)
        for _attempt in range(deadline):
            kube_pod = self.kubernetes.get_pod(ns, pod)
            if kube_pod.phase == "Running" and kube_pod.pod_ip != "":
                new_pod.ip = kube_pod.pod_ip
                return
            time.sleep(self.pod_wait_sleep_seconds)
        raise KubeError(
            f"unable to wait for running or get pod ip for {ns}/{pod} after creation"
        )

    def set_pod_labels(self, ns: str, pod: str, labels: Dict[str, str]) -> None:
        self.resources = self.resources.set_pod_labels(ns, pod, labels)
        self.kubernetes.set_pod_labels(ns, pod, labels)

    def delete_pod(self, ns: str, pod: str) -> None:
        deleted_pod = self.resources.get_pod(ns, pod)
        self.resources = self.resources.delete_pod(ns, pod)
        self.kubernetes.delete_service(ns, deleted_pod.kube_service().name)
        self.kubernetes.delete_pod(ns, pod)

    # --- reset / verify (testcasestate.go:291-331) ---

    def reset_cluster_state(self) -> None:
        delete_all_network_policies_in_namespaces(
            self.kubernetes, self.resources.namespaces_slice()
        )
        for ns, labels in self.resources.namespaces.items():
            self.kubernetes.set_namespace_labels(ns, labels)
        for pod in self.resources.pods:
            self.kubernetes.set_pod_labels(pod.namespace, pod.name, pod.labels)

    def verify_cluster_state(self) -> None:
        self._verify_cluster_state_helper()
        policies = get_network_policies_in_namespaces(
            self.kubernetes, self.resources.namespaces_slice()
        )
        if policies:
            raise KubeError(
                f"expected 0 policies in namespaces "
                f"{self.resources.namespaces_slice()}, found {len(policies)}"
            )

    def _verify_cluster_state_helper(self) -> None:
        """Deep-compare pods/services/namespaces (testcasestate.go:183-249)."""
        kube_pods = get_pods_in_namespaces(
            self.kubernetes, self.resources.namespaces_slice()
        )
        actual_pods = {f"{p.namespace}/{p.name}": p for p in kube_pods}

        for expected_pod in self.resources.pods:
            key = str(expected_pod.pod_string())
            if key not in actual_pods:
                raise KubeError(f"missing expected pod {key}")
            actual = actual_pods[key]
            if not LabelsDiff.compare(actual.labels, expected_pod.labels).are_labels_equal():
                raise KubeError(
                    f"for pod {key}, expected labels {expected_pod.labels} "
                    f"(found {actual.labels})"
                )
            if actual.pod_ip != expected_pod.ip:
                raise KubeError(
                    f"for pod {key}, expected ip {expected_pod.ip} "
                    f"(found {actual.pod_ip})"
                )
            if not expected_pod.is_equal_to_kube_pod(actual):
                raise KubeError(
                    f"for pod {key}, expected containers "
                    f"{expected_pod.containers} (found {actual.containers})"
                )

        for expected_pod in self.resources.pods:
            expected_svc = expected_pod.kube_service()
            svc = self.kubernetes.get_service(expected_svc.namespace, expected_svc.name)
            if not LabelsDiff.compare(svc.selector, expected_pod.labels).are_labels_equal():
                raise KubeError(
                    f"for service {expected_pod.namespace}/{expected_pod.name}, "
                    f"expected labels {expected_pod.labels} (found {svc.selector})"
                )
            if len(expected_svc.ports) != len(svc.ports):
                raise KubeError(
                    f"for service {expected_svc.namespace}/{expected_svc.name}, "
                    f"expected {len(expected_svc.ports)} ports (found {len(svc.ports)})"
                )
            for expected_port, kube_port in zip(expected_svc.ports, svc.ports):
                if (
                    kube_port.protocol != expected_port.protocol
                    or kube_port.port != expected_port.port
                ):
                    raise KubeError(
                        f"for service {expected_svc.namespace}/{expected_svc.name}, "
                        f"expected port {expected_port} (found {kube_port})"
                    )

        for ns, expected_labels in self.resources.namespaces.items():
            namespace = self.kubernetes.get_namespace(ns)
            diff = LabelsDiff.compare(namespace.labels, expected_labels)
            if not diff.are_all_expected_labels_present():
                raise KubeError(
                    f"for namespace {ns}, expected labels {expected_labels} "
                    f"(found {namespace.labels})"
                )
