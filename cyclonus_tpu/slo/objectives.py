"""The declared service-level objectives: a small code-declared
registry (the engine/planspec.py discipline — declarations are live
code the controller consumes, not documentation) over signals the
telemetry stack already emits.

Four objectives ship, one per signal family:

  * ``query_p99`` — per-flow query latency, from the
    cyclonus_tpu_serve_query_latency_seconds histogram.  An event is
    one answered flow; bad means slower than the target.
  * ``freshness`` — delta-apply freshness, from the pending-queue wait
    age (cyclonus_tpu_serve_staleness_seconds's source value).  An
    event is one accounting tick; bad means the oldest pending delta
    has waited longer than the target.
  * ``ttfv`` — time-to-first-verdict after a (re)start, observed once
    per process.  Bad means the first verdict took longer than the
    target — the restart contract the chaos harness kills replicas to
    check.
  * ``verdict_integrity`` — shadow-oracle audit divergences, from the
    cumulative cyclonus_tpu_audit_checked/diverged counters
    (cyclonus_tpu/audit).  An event is one audited verdict; bad means
    the served allow bits disagreed with the scalar oracle.  Breach-
    dump posture like ttfv: a divergence is forensic evidence, never a
    reason to block queries.

Every numeric knob is env-tunable through utils/envflags.py (the
``CYCLONUS_SLO_QUERY_P99_S``-style slo flag family) so a drill can
shrink targets/windows to force
enforcement without code changes; the DECLARATIONS (which objectives
exist, what signal each reads, what enforcement it governs) are code.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

from ..utils import envflags

#: objective signal kinds
HISTOGRAM = "histogram"  # cumulative latency histogram snapshots
GAUGE = "gauge"          # one threshold sample per accounting tick
ONCE = "once"            # a single per-process observation
COUNTER = "counter"      # cumulative (total, bad) counter pair


@dataclass(frozen=True)
class Objective:
    """One declared SLO: the signal it reads, the target that splits
    good from bad events, the burn windows, and the error budget."""

    name: str
    kind: str  # HISTOGRAM | GAUGE | ONCE | COUNTER
    signal: str  # the telemetry signal the objective is computed from
    target_s: float  # seconds: the good/bad event threshold
    budget: float  # error budget: tolerated bad-event fraction
    fast_s: float  # fast burn window (seconds)
    slow_s: float  # slow burn window (seconds)
    enforces: str  # the enforcement lever this objective governs
    description: str


def declared_objectives() -> Tuple[Objective, ...]:
    """The registry, with targets/windows resolved from the environment
    (never-raise envflags accessors, so a malformed value degrades to
    the declared default instead of killing the service)."""
    budget = envflags.get_float("CYCLONUS_SLO_BUDGET")
    fast_s = envflags.get_float("CYCLONUS_SLO_FAST_S")
    slow_s = envflags.get_float("CYCLONUS_SLO_SLOW_S")
    return (
        Objective(
            name="query_p99",
            kind=HISTOGRAM,
            signal="cyclonus_tpu_serve_query_latency_seconds",
            target_s=envflags.get_float("CYCLONUS_SLO_QUERY_P99_S"),
            budget=budget,
            fast_s=fast_s,
            slow_s=slow_s,
            enforces="shed/degrade",
            description=(
                "per-flow query latency: burning routes queries onto "
                "the scalar-oracle degraded path, exhaustion sheds "
                "with a typed refusal"
            ),
        ),
        Objective(
            name="freshness",
            kind=GAUGE,
            signal="cyclonus_tpu_serve_staleness_seconds",
            target_s=envflags.get_float("CYCLONUS_SLO_FRESHNESS_S"),
            budget=budget,
            fast_s=fast_s,
            slow_s=slow_s,
            enforces="admission",
            description=(
                "delta-apply freshness (oldest pending delta's wait "
                "age): burning caps the pending queue, exhaustion "
                "rejects delta intake until the backlog drains"
            ),
        ),
        Objective(
            name="ttfv",
            kind=ONCE,
            signal="first verdict wall-clock after process start",
            target_s=envflags.get_float("CYCLONUS_SLO_TTFV_S"),
            budget=budget,
            fast_s=fast_s,
            slow_s=slow_s,
            enforces="breach-dump",
            description=(
                "time-to-first-verdict after restart: exceeding the "
                "target is an immediate breach (black-box dump); the "
                "chaos harness kills a replica mid-churn to check it"
            ),
        ),
        Objective(
            name="verdict_integrity",
            kind=COUNTER,
            signal="cyclonus_tpu_audit_diverged_total",
            # target_s is unused for a counter objective (good/bad is
            # decided at the signal: a diverged check IS a bad event);
            # declared 0.0 so the snapshot schema stays uniform.
            target_s=0.0,
            budget=budget,
            fast_s=fast_s,
            slow_s=slow_s,
            enforces="breach-dump",
            description=(
                "shadow-oracle verdict integrity: any audited verdict "
                "disagreeing with the scalar oracle burns budget and "
                "exhaustion dumps the black box (audit-divergence "
                "bundles carry the repro) — never query-blocking"
            ),
        ),
    )
