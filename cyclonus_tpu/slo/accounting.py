"""Multi-window burn-rate accounting: the SLO engine's math, with no
telemetry or service dependencies so tests can drive it against
synthetic event streams with an injected clock.

The model is the Google-SRE multi-window burn-rate alert, applied
in-process:

  * Every objective consumes a cumulative (total, bad) event stream —
    for the query-latency objective an event is one answered flow and
    "bad" means slower than the target; for the freshness objective an
    event is one accounting tick and "bad" means the oldest pending
    delta is older than the target.
  * The burn rate over a trailing window is
    ``bad_fraction(window) / error_budget`` — 1.0 means the budget is
    being spent exactly as fast as it accrues, N means N times faster.
  * Enforcement looks at a FAST and a SLOW window together: the fast
    window makes entry responsive, the slow window keeps a transient
    spike from flapping the state.  Budget remaining is
    ``1 - burn(slow)``, clamped to [0, 1] — 0 means the slow window's
    budget is fully spent (the breach transition).

The hysteresis state machine (``ok -> burning -> exhausted``) enters
eagerly and exits lazily: BURNING engages the moment the FAST window
burns past the enter threshold (the slow window cannot gate entry —
any slow burn past 1.0 already means the budget is spent, i.e.
EXHAUSTED, so a slow-window entry bar above 1.0 would be unreachable),
EXHAUSTED fires when the slow window's budget hits zero, and the
machine disengages only after BOTH windows have stayed below the exit
threshold for a continuous hold period — so a load spike that
oscillates around the threshold cannot flap shed/admission decisions
on and off.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

#: enforcement states, in severity order
OK = "ok"
BURNING = "burning"
EXHAUSTED = "exhausted"

_SEVERITY = {OK: 0, BURNING: 1, EXHAUSTED: 2}


def state_severity(state: str) -> int:
    """Numeric severity for gauges (0 ok / 1 burning / 2 exhausted)."""
    return _SEVERITY.get(state, 0)


@dataclass(frozen=True)
class BurnSample:
    """One cumulative observation: by time `at`, `total` events had
    happened, `bad` of them out of objective."""

    at: float
    total: float
    bad: float


class BurnAccountant:
    """Burn-rate evaluation over a cumulative (total, bad) stream.

    Observations are CUMULATIVE totals (monotone non-decreasing), so
    feeding histogram snapshot counts needs no per-interval diffing by
    the caller — the accountant diffs against the sample just outside
    each trailing window.  Not thread-safe by itself; the controller
    serializes access.
    """

    def __init__(self, budget: float, fast_s: float, slow_s: float):
        if fast_s > slow_s:
            fast_s, slow_s = slow_s, fast_s
        self.budget = max(float(budget), 1e-9)
        self.fast_s = float(fast_s)
        self.slow_s = float(slow_s)
        self._samples: List[BurnSample] = []

    def observe(self, now: float, total: float, bad: float) -> None:
        """Record cumulative totals as of `now`.  A stream that moves
        backwards (registry reset between ticks) restarts the window."""
        if self._samples and (
            total < self._samples[-1].total or bad < self._samples[-1].bad
        ):
            self._samples = []
        self._samples.append(BurnSample(now, float(total), float(bad)))
        # keep exactly one sample older than the slow window so the
        # window delta always has a baseline to diff against
        horizon = now - self.slow_s
        while len(self._samples) >= 2 and self._samples[1].at <= horizon:
            self._samples.pop(0)

    def _window_delta(self, now: float, window_s: float) -> Tuple[float, float]:
        """(events, bad events) inside the trailing window."""
        if not self._samples:
            return 0.0, 0.0
        latest = self._samples[-1]
        cutoff = now - window_s
        base: Optional[BurnSample] = None
        for s in self._samples:
            if s.at <= cutoff:
                base = s
            else:
                break
        if base is None:
            # stream younger than the window: everything seen counts
            return latest.total, latest.bad
        return latest.total - base.total, latest.bad - base.bad

    def bad_fraction(self, now: float, window_s: float) -> float:
        total, bad = self._window_delta(now, window_s)
        if total <= 0:
            return 0.0
        return min(1.0, max(0.0, bad / total))

    def burn_rate(self, now: float, window_s: float) -> float:
        return self.bad_fraction(now, window_s) / self.budget

    def burn_rates(self, now: float) -> Tuple[float, float]:
        """(fast, slow) burn rates."""
        return (
            self.burn_rate(now, self.fast_s),
            self.burn_rate(now, self.slow_s),
        )

    def budget_remaining(self, now: float) -> float:
        """Fraction of the slow window's error budget left, in [0, 1]."""
        return min(1.0, max(0.0, 1.0 - self.burn_rate(now, self.slow_s)))


class Hysteresis:
    """The ok -> burning -> exhausted state machine: eager entry, held
    exit (see module docstring).  Pure function of the fed rate stream
    and the injected clock, so tests can pin exact entry/exit instants.
    """

    def __init__(
        self,
        enter_burn: float = 2.0,
        exit_burn: float = 1.0,
        hold_s: float = 60.0,
    ):
        self.enter_burn = float(enter_burn)
        self.exit_burn = float(exit_burn)
        self.hold_s = float(hold_s)
        self.state = OK
        self.since: Optional[float] = None  # when `state` was entered
        self._clear_since: Optional[float] = None
        self.transitions = 0

    def _move(self, now: float, state: str) -> None:
        if state != self.state:
            self.state = state
            self.since = now
            self.transitions += 1

    def update(
        self, now: float, fast_burn: float, slow_burn: float, remaining: float
    ) -> str:
        """Advance the machine; returns the (possibly new) state."""
        if remaining <= 0.0:
            self._clear_since = None
            self._move(now, EXHAUSTED)
            return self.state
        if fast_burn >= self.enter_burn:
            self._clear_since = None
            if _SEVERITY[self.state] < _SEVERITY[BURNING]:
                self._move(now, BURNING)
            return self.state
        # below the enter threshold: exit only after a continuous hold
        # below the EXIT threshold (the gap between the two thresholds
        # plus the hold is the anti-flap margin)
        if fast_burn < self.exit_burn and slow_burn < self.exit_burn:
            if self._clear_since is None:
                self._clear_since = now
            if now - self._clear_since >= self.hold_s:
                self._move(now, OK)
        else:
            self._clear_since = None
        return self.state
