"""SloController: turns the declared objectives (objectives.py) plus
the burn-rate math (accounting.py) into live enforcement decisions for
the verdict service, and into the `cyclonus_tpu_slo_*` gauge family +
the `/slo` JSON payload.

Wiring (docs/DESIGN.md "SLO engine"):

  * VerdictService owns one controller.  Its scrape-time collector
    (`_refresh_gauges`) calls `tick()` — so burn accounting advances on
    the SAME cadence the staleness gauges already refresh on, and a
    process nobody scrapes pays nothing.
  * `query_route()` / `admit()` are the enforcement reads on the hot
    paths: lock-cheap, never raise, and constant "live"/None while
    enforcement is disarmed (CYCLONUS_SLO_ENFORCE, default off — the
    accounting and the /slo surface are always on, the levers are
    opt-in).
  * On a transition into `exhausted`, the controller records a breach
    entry (current trace id + span path as exemplars) and dumps the
    flight recorder with reason "slo-breach:<objective>" — the black
    box a post-mortem opens first.

Lock order: controller lock -> metric locks only; the controller never
takes the service lock, so service._lock -> slo._lock is the one
cross-object edge (submit/query hold the service lock while asking for
a decision) and the graph stays acyclic (tools/locklint.py LK002).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

from ..telemetry import instruments as ti
from ..telemetry import recorder
from ..utils import guards
from . import accounting
from .accounting import BURNING, EXHAUSTED, OK, BurnAccountant, Hysteresis
from .objectives import (
    COUNTER,
    GAUGE,
    HISTOGRAM,
    ONCE,
    Objective,
    declared_objectives,
)


def events_over_target(snapshot: Dict, target_s: float) -> Dict[str, float]:
    """(total, bad) cumulative event counts from a telemetry Histogram
    snapshot: bad = events that landed in a bucket whose upper bound
    exceeds the target (label series merged).  Bucket-resolution by
    construction — the same resolution /state's quantiles already have.
    """
    buckets = snapshot.get("buckets") or []
    total = 0
    good = 0
    for s in snapshot.get("samples") or []:
        total += int(s.get("count", 0))
        for ub, c in zip(buckets, s.get("counts") or []):
            if ub <= target_s:
                good += int(c)
    return {"total": float(total), "bad": float(max(0, total - good))}


class _Tracker:
    """One objective's live state: accountant + hysteresis + the last
    computed rates (cached for lock-cheap snapshot/decision reads)."""

    def __init__(self, obj: Objective, enter: float, exit_: float, hold: float):
        self.obj = obj
        self.acct = BurnAccountant(obj.budget, obj.fast_s, obj.slow_s)
        self.hyst = Hysteresis(enter, exit_, hold)
        self.fast_burn = 0.0
        self.slow_burn = 0.0
        self.remaining = 1.0
        self.forced: Optional[str] = None

    @property
    def state(self) -> str:
        return self.forced if self.forced is not None else self.hyst.state

    def advance(self, now: float) -> bool:
        """Recompute rates and step the hysteresis; True on a transition
        INTO exhausted (the breach edge)."""
        self.fast_burn, self.slow_burn = self.acct.burn_rates(now)
        self.remaining = self.acct.budget_remaining(now)
        was = self.hyst.state
        state = self.hyst.update(
            now, self.fast_burn, self.slow_burn, self.remaining
        )
        return state == EXHAUSTED and was != EXHAUSTED


@guards.checked
class SloController:
    """See the module docstring."""

    _trackers = guards.Guarded("_lock")
    _ticks = guards.Guarded("_lock")
    _ttfv_noted = guards.Guarded("_lock")

    def __init__(
        self,
        objectives: Optional[List[Objective]] = None,
        *,
        enforce: Optional[bool] = None,
        queue_cap: Optional[int] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        from ..utils import envflags

        self._lock = guards.lock()
        self._clock = clock
        self._started = clock()
        self.enforce = (
            envflags.get_bool("CYCLONUS_SLO_ENFORCE")
            if enforce is None
            else bool(enforce)
        )
        self.queue_cap = (
            envflags.get_int("CYCLONUS_SLO_QUEUE_CAP")
            if queue_cap is None
            else int(queue_cap)
        )
        enter = envflags.get_float("CYCLONUS_SLO_ENTER_BURN")
        exit_ = envflags.get_float("CYCLONUS_SLO_EXIT_BURN")
        hold = envflags.get_float("CYCLONUS_SLO_HOLD_S")
        objs = (
            list(objectives)
            if objectives is not None
            else list(declared_objectives())
        )
        self._trackers: Dict[str, _Tracker] = {
            o.name: _Tracker(o, enter, exit_, hold) for o in objs
        }
        self._ticks = 0
        self._ttfv_noted = False

    # --- signal intake ----------------------------------------------------

    def tick(
        self,
        *,
        staleness_s: Optional[float] = None,
        latency_snapshot: Optional[Dict] = None,
        now: Optional[float] = None,
    ) -> None:
        """One accounting step (the _refresh_gauges cadence): fold the
        latency histogram and the staleness sample into the accountants,
        advance every hysteresis, export the slo gauges, and dump the
        black box on a budget-exhaustion edge.  Never raises — a broken
        signal must not break the scrape that drives it."""
        try:
            self._tick(staleness_s, latency_snapshot, now)
        except Exception:
            pass  # never break the scrape path

    def _tick(
        self,
        staleness_s: Optional[float],
        latency_snapshot: Optional[Dict],
        now: Optional[float],
    ) -> None:
        if latency_snapshot is None:
            latency_snapshot = ti.SERVE_QUERY_LATENCY.snapshot()
        t = self._clock() if now is None else now
        breached: List[_Tracker] = []
        with self._lock:
            self._ticks += 1
            for tr in self._trackers.values():
                obj = tr.obj
                if obj.kind == HISTOGRAM:
                    ev = events_over_target(latency_snapshot, obj.target_s)
                    tr.acct.observe(t, ev["total"], ev["bad"])
                elif obj.kind == GAUGE:
                    if staleness_s is None:
                        continue  # contended refresh: no sample this tick
                    last = tr.acct._samples[-1] if tr.acct._samples else None
                    total = (last.total if last else 0.0) + 1.0
                    bad = (last.bad if last else 0.0) + (
                        1.0 if staleness_s > obj.target_s else 0.0
                    )
                    tr.acct.observe(t, total, bad)
                elif obj.kind == COUNTER:
                    # cumulative (total, bad) straight off the audit
                    # counters — the same shape the histogram fold
                    # produces, so the accountant diffs it identically
                    tr.acct.observe(
                        t,
                        float(ti.AUDIT_CHECKED.value()),
                        float(ti.AUDIT_DIVERGED.value()),
                    )
                # ONCE objectives advance only via observe_ttfv
                if tr.advance(t):
                    breached.append(tr)
            trackers = list(self._trackers.values())
        for tr in trackers:
            self._export(tr)
        for tr in breached:
            self._breach(tr)

    def observe_ttfv(self, seconds: float, now: Optional[float] = None) -> None:
        """Feed the once-per-process time-to-first-verdict observation:
        a single event, bad iff over target — so an over-budget restart
        is an immediate exhaustion (and breach dump)."""
        t = self._clock() if now is None else now
        with self._lock:
            tr = self._trackers.get("ttfv")
            if tr is None:
                return
            tr.acct.observe(t, 1.0, 1.0 if seconds > tr.obj.target_s else 0.0)
            breach = tr.advance(t)
        self._export(tr)
        if breach:
            self._breach(tr, extra={"ttfv_s": round(float(seconds), 3)})

    def note_first_verdict(self) -> None:
        """Idempotent hook the service's query paths call after every
        answered batch: the first call stamps time-to-first-verdict as
        now - controller creation (the service constructs its controller
        at boot, so this spans rebuild + prewarm)."""
        with self._lock:
            if self._ttfv_noted:
                return
            self._ttfv_noted = True
        self.observe_ttfv(self._clock() - self._started)

    # --- enforcement decisions (hot-path reads) ---------------------------

    def state_of(self, objective: str) -> str:
        with self._lock:
            tr = self._trackers.get(objective)
            return tr.state if tr is not None else OK

    def query_route(self) -> str:
        """The query path's routing decision: "shed" (typed refusal)
        when the latency budget is exhausted, "degraded" (scalar-oracle
        path — no service-lock wait behind a rebuild) while it burns,
        "live" otherwise or whenever enforcement is disarmed."""
        if not self.enforce:
            return "live"
        state = self.state_of("query_p99")
        if state == EXHAUSTED:
            return "shed"
        if state == BURNING:
            return "degraded"
        return "live"

    def admit(self, pending_depth: int, incoming: int) -> Optional[str]:
        """Admission control for submit(): None admits; a string is the
        rejection reason (freshness budget exhausted, or burning with
        the pending queue at cap)."""
        if not self.enforce:
            return None
        state = self.state_of("freshness")
        if state == EXHAUSTED:
            return (
                "freshness error budget exhausted: delta intake "
                "suspended until the backlog drains"
            )
        if state == BURNING and pending_depth + incoming > self.queue_cap:
            return (
                f"freshness budget burning: pending queue capped at "
                f"{self.queue_cap} (depth {pending_depth}, "
                f"incoming {incoming})"
            )
        return None

    def force_state(self, objective: str, state: Optional[str]) -> None:
        """Pin an objective's state (tests, drills, the route harness);
        None releases the pin.  Forced state feeds the same decision
        and gauge paths as computed state."""
        if state is not None and state not in (OK, BURNING, EXHAUSTED):
            raise ValueError(f"unknown slo state {state!r}")
        with self._lock:
            tr = self._trackers[objective]
            tr.forced = state
        self._export(tr)

    # --- export -----------------------------------------------------------

    def _export(self, tr: _Tracker) -> None:
        obj = tr.obj
        ti.SLO_BURN_RATE.set(
            tr.fast_burn, objective=obj.name, window="fast"
        )
        ti.SLO_BURN_RATE.set(
            tr.slow_burn, objective=obj.name, window="slow"
        )
        ti.SLO_BUDGET_REMAINING.set(tr.remaining, objective=obj.name)
        ti.SLO_STATE.set(
            accounting.state_severity(tr.state), objective=obj.name
        )

    def _breach(self, tr: _Tracker, extra: Optional[Dict] = None) -> None:
        """The budget-exhaustion edge: black-box capture.  The breach
        entry carries the live trace/span ids as exemplars so the dump
        correlates with any active timeline, then the whole flight ring
        goes to disk with the triggering objective in the reason."""
        from ..telemetry import events, spans

        obj = tr.obj
        ti.SLO_BREACHES.inc(objective=obj.name)
        entry = {
            "path": "slo.breach",
            "objective": obj.name,
            "signal": obj.signal,
            "target_s": obj.target_s,
            "burn_fast": round(tr.fast_burn, 4),
            "burn_slow": round(tr.slow_burn, 4),
            "budget_remaining": round(tr.remaining, 4),
            "trace_id": events.trace_id(),
            "span_path": spans.current_path(),
        }
        if extra:
            entry.update(extra)
        try:
            recorder.record(**entry)
            recorder.dump(reason=f"slo-breach:{obj.name}")
        except Exception:
            pass  # the dump is forensics; failing to write it must not
            # take the enforcement path down with it

    def snapshot(self) -> Dict:
        """The /slo payload: per-objective budget remaining, burn
        rates, and enforcement state (key set pinned by test)."""
        with self._lock:
            trackers = list(self._trackers.values())
            ticks = self._ticks
        objectives = {}
        for tr in trackers:
            obj = tr.obj
            objectives[obj.name] = {
                "signal": obj.signal,
                "target_s": obj.target_s,
                "budget": obj.budget,
                "windows": {"fast_s": obj.fast_s, "slow_s": obj.slow_s},
                "burn": {
                    "fast": round(tr.fast_burn, 4),
                    "slow": round(tr.slow_burn, 4),
                },
                "budget_remaining": round(tr.remaining, 4),
                "state": tr.state,
                "enforces": obj.enforces,
                "breaches": int(
                    ti.SLO_BREACHES.value(objective=obj.name)
                ),
            }
        return {
            "enforce": self.enforce,
            "queue_cap": self.queue_cap,
            "ticks": ticks,
            "shed_queries": int(ti.SLO_SHED.value()),
            "admission_rejects": int(ti.SLO_ADMISSION_REJECTS.value()),
            "objectives": objectives,
        }
