"""SLO engine: declarative objectives over signals the telemetry
stack already emits, multi-window burn-rate accounting, and in-service
enforcement (admission control, load shedding, degraded-path governance)
with breach black-box capture.  See docs/DESIGN.md "SLO engine".
"""

from .accounting import (
    BURNING,
    EXHAUSTED,
    OK,
    BurnAccountant,
    BurnSample,
    Hysteresis,
    state_severity,
)
from .engine import SloController, events_over_target
from .objectives import GAUGE, HISTOGRAM, ONCE, Objective, declared_objectives

__all__ = [
    "OK",
    "BURNING",
    "EXHAUSTED",
    "BurnAccountant",
    "BurnSample",
    "Hysteresis",
    "state_severity",
    "SloController",
    "events_over_target",
    "HISTOGRAM",
    "GAUGE",
    "ONCE",
    "Objective",
    "declared_objectives",
]
