"""YAML loading/serialization of NetworkPolicies (reference: pkg/cli/utils.go).

Supports the same input shapes: a single policy document, a YAML list, a
multi-doc stream, a `kind: NetworkPolicyList`, or a directory walked
recursively for .yml/.yaml files (utils.go:14-60).
"""

from __future__ import annotations

import os
from typing import List, Optional

import yaml

from .netpol import NetworkPolicy, NetworkPolicySpec


def parse_policy_dict(d: dict) -> NetworkPolicy:
    meta = d.get("metadata") or {}
    return NetworkPolicy(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", ""),
        spec=NetworkPolicySpec.from_dict(d.get("spec") or {}),
    )


def policy_to_dict(p: NetworkPolicy) -> dict:
    meta: dict = {"name": p.name}
    if p.namespace:
        meta["namespace"] = p.namespace
    return {
        "apiVersion": "networking.k8s.io/v1",
        "kind": "NetworkPolicy",
        "metadata": meta,
        "spec": p.spec.to_dict(),
    }


def policies_to_yaml(policies: List[NetworkPolicy]) -> str:
    return yaml.safe_dump_all(
        [policy_to_dict(p) for p in policies], sort_keys=False, default_flow_style=False
    )


def _parse_documents(docs) -> List[NetworkPolicy]:
    policies: List[NetworkPolicy] = []
    for doc in docs:
        if doc is None:
            continue
        if isinstance(doc, list):
            for item in doc:
                policies.append(parse_policy_dict(item))
        elif isinstance(doc, dict) and doc.get("kind") == "NetworkPolicyList":
            for item in doc.get("items") or []:
                policies.append(parse_policy_dict(item))
        elif isinstance(doc, dict):
            policies.append(parse_policy_dict(doc))
        else:
            raise ValueError(f"unexpected YAML document of type {type(doc)}")
    return policies


def load_policies_from_yaml(text: str) -> List[NetworkPolicy]:
    return _parse_documents(yaml.safe_load_all(text))


def load_policies_from_file(path: str) -> List[NetworkPolicy]:
    with open(path) as f:
        return load_policies_from_yaml(f.read())


def load_policies_from_path(path: str) -> List[NetworkPolicy]:
    """File => parse it; directory => recursive walk of .yml/.yaml files
    (utils.go:14-60)."""
    if os.path.isdir(path):
        policies: List[NetworkPolicy] = []
        for root, _dirs, files in sorted(os.walk(path)):
            for name in sorted(files):
                if name.endswith((".yml", ".yaml")):
                    policies.extend(load_policies_from_file(os.path.join(root, name)))
        return policies
    return load_policies_from_file(path)
