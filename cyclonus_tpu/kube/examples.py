"""Canned example policies (reference: pkg/kube/netpol/policies.go +
kubedocs.go): parameterized builders for ahmetb's public
kubernetes-network-policy-recipes plus the kube-docs accidental-and/or
examples.  Used by `analyze --use-example-policies` and tests."""

from __future__ import annotations

from typing import Dict, List

from .netpol import (
    IntOrString,
    LabelSelector,
    NetworkPolicy,
    NetworkPolicyEgressRule,
    NetworkPolicyIngressRule,
    NetworkPolicyPeer,
    NetworkPolicyPort,
    NetworkPolicySpec,
)


def label_string(labels: Dict[str, str]) -> str:
    """Deterministic key-val1-key2-val2 name chunk (policies.go:17-33)."""
    chunks: List[str] = []
    for key in sorted(labels):
        chunks.extend([key, labels[key]])
    return "-".join(chunks)


def _sel(labels: Dict[str, str]) -> LabelSelector:
    return LabelSelector.make(match_labels=labels)


def _policy(name, ns, pod_selector, types, ingress=None, egress=None):
    return NetworkPolicy(
        name=name,
        namespace=ns,
        spec=NetworkPolicySpec(
            pod_selector=pod_selector,
            policy_types=types,
            ingress=ingress or [],
            egress=egress or [],
        ),
    )


# recipe 01: deny all traffic to an application
def allow_nothing_to(ns: str, to_labels: Dict[str, str]) -> NetworkPolicy:
    return _policy(
        f"allow-nothing-to-{label_string(to_labels)}", ns, _sel(to_labels), ["Ingress"]
    )


def allow_nothing_to_empty_ingress(ns: str, to_labels: Dict[str, str]) -> NetworkPolicy:
    return _policy(
        f"allow-nothing-to-v2-{label_string(to_labels)}", ns, _sel(to_labels), ["Ingress"]
    )


# recipe 02: limit traffic to an application
def allow_from_to(
    ns: str, from_labels: Dict[str, str], to_labels: Dict[str, str]
) -> NetworkPolicy:
    return _policy(
        f"allow-from-{label_string(from_labels)}-to-{label_string(to_labels)}",
        ns,
        _sel(to_labels),
        ["Ingress"],
        ingress=[
            NetworkPolicyIngressRule(
                from_=[NetworkPolicyPeer(pod_selector=_sel(from_labels))]
            )
        ],
    )


# recipe 02a: allow all traffic to an application
def allow_all_to(ns: str, to_labels: Dict[str, str]) -> NetworkPolicy:
    return _policy(
        f"allow-all-to-{label_string(to_labels)}",
        ns,
        _sel(to_labels),
        ["Ingress"],
        ingress=[NetworkPolicyIngressRule()],
    )


# recipe 03: default deny all in namespace
def allow_nothing_to_anything(ns: str) -> NetworkPolicy:
    return _policy("allow-nothing-to-anything", ns, LabelSelector.make(), ["Ingress"])


# recipe 04: deny traffic from other namespaces
def allow_all_within_namespace(ns: str) -> NetworkPolicy:
    return _policy(
        "allow-all-within-namespace",
        ns,
        LabelSelector.make(),
        ["Ingress"],
        ingress=[
            NetworkPolicyIngressRule(
                from_=[NetworkPolicyPeer(pod_selector=LabelSelector.make())]
            )
        ],
    )


# recipe 05 variants: allow from all namespaces
def allow_all_to_version2(ns: str, to_labels: Dict[str, str]) -> NetworkPolicy:
    return _policy(
        f"allow-all-to-version2-{label_string(to_labels)}",
        ns,
        _sel(to_labels),
        ["Ingress"],
        ingress=[
            NetworkPolicyIngressRule(
                from_=[NetworkPolicyPeer(namespace_selector=LabelSelector.make())]
            )
        ],
    )


def allow_all_to_version3(ns: str, to_labels: Dict[str, str]) -> NetworkPolicy:
    return _policy(
        f"allow-all-to-version3-{label_string(to_labels)}",
        ns,
        _sel(to_labels),
        ["Ingress"],
        ingress=[NetworkPolicyIngressRule()],
    )


def allow_all_to_version4(ns: str, to_labels: Dict[str, str]) -> NetworkPolicy:
    return _policy(
        f"allow-all-to-version4-{label_string(to_labels)}",
        ns,
        _sel(to_labels),
        ["Ingress"],
        ingress=[
            NetworkPolicyIngressRule(
                from_=[
                    NetworkPolicyPeer(
                        pod_selector=LabelSelector.make(),
                        namespace_selector=LabelSelector.make(),
                    )
                ]
            )
        ],
    )


# recipe 06: allow traffic from a namespace
def allow_from_namespace_to(
    ns: str, namespace_labels: Dict[str, str], to_labels: Dict[str, str]
) -> NetworkPolicy:
    return _policy(
        f"allow-from-namespace-to-{label_string(to_labels)}",
        ns,
        _sel(to_labels),
        ["Ingress"],
        ingress=[
            NetworkPolicyIngressRule(
                from_=[NetworkPolicyPeer(namespace_selector=_sel(namespace_labels))]
            )
        ],
    )


# recipe 07: allow traffic from some pods in another namespace
def allow_from_different_namespace_with_labels_to(
    ns: str,
    from_labels: Dict[str, str],
    namespace_labels: Dict[str, str],
    to_labels: Dict[str, str],
) -> NetworkPolicy:
    return _policy(
        f"allow-from-namespace-with-labels-{label_string(from_labels)}-to-"
        f"{label_string(to_labels)}",
        ns,
        _sel(to_labels),
        ["Ingress"],
        ingress=[
            NetworkPolicyIngressRule(
                from_=[
                    NetworkPolicyPeer(
                        pod_selector=_sel(from_labels),
                        namespace_selector=_sel(namespace_labels),
                    )
                ]
            )
        ],
    )


# recipe 08: allow external traffic
def allow_from_anywhere(ns: str, to_labels: Dict[str, str]) -> NetworkPolicy:
    return _policy(
        f"allow-from-anywhere-to-{label_string(to_labels)}",
        ns,
        _sel(to_labels),
        ["Ingress"],
        ingress=[NetworkPolicyIngressRule(from_=[])],
    )


# recipe 09: allow traffic only to a port
def allow_specific_port_to(
    ns: str, from_labels: Dict[str, str], to_labels: Dict[str, str], port: int
) -> NetworkPolicy:
    return _policy(
        f"allow-specific-port-from-{label_string(from_labels)}-to-"
        f"{label_string(to_labels)}",
        ns,
        _sel(to_labels),
        ["Ingress"],
        ingress=[
            NetworkPolicyIngressRule(
                ports=[NetworkPolicyPort(port=IntOrString(port))],
                from_=[NetworkPolicyPeer(pod_selector=_sel(from_labels))],
            )
        ],
    )


# recipe 10: allow traffic from multiple sources
def allow_from_multiple_to(
    ns: str, from_labels: List[Dict[str, str]], to_labels: Dict[str, str]
) -> NetworkPolicy:
    return _policy(
        f"allow-from-multiple-to-{label_string(to_labels)}",
        ns,
        _sel(to_labels),
        ["Ingress"],
        ingress=[
            NetworkPolicyIngressRule(
                from_=[
                    NetworkPolicyPeer(pod_selector=_sel(labels))
                    for labels in from_labels
                ]
            )
        ],
    )


# recipe 11: deny egress from an application
def allow_no_egress_from_labels(ns: str, to_labels: Dict[str, str]) -> NetworkPolicy:
    return _policy(
        f"allow-no-egress-from-labels-{label_string(to_labels)}",
        ns,
        _sel(to_labels),
        ["Egress"],
    )


# recipe 11a: allow dns egress
def allow_egress_on_port(ns: str, to_labels: Dict[str, str], port: int) -> NetworkPolicy:
    return _policy(
        f"allow-egress-on-port-{label_string(to_labels)}",
        ns,
        _sel(to_labels),
        ["Egress"],
        egress=[
            NetworkPolicyEgressRule(
                ports=[
                    NetworkPolicyPort(protocol="TCP", port=IntOrString(port)),
                    NetworkPolicyPort(protocol="UDP", port=IntOrString(port)),
                ]
            )
        ],
    )


# recipe 12: deny all egress from a namespace
def allow_no_egress_from_namespace(ns: str) -> NetworkPolicy:
    return _policy(
        "allow-no-egress-from-namespace", ns, LabelSelector.make(), ["Egress"]
    )


# recipe 14: deny external egress
def allow_egress_to_all_namespaces_on_port(
    ns: str, to_labels: Dict[str, str], port: int
) -> NetworkPolicy:
    return _policy(
        f"allow-egress-to-all-namespace-from-{label_string(to_labels)}-on-port-{port}",
        ns,
        _sel(to_labels),
        ["Egress"],
        egress=[
            NetworkPolicyEgressRule(
                ports=[
                    NetworkPolicyPort(protocol="TCP", port=IntOrString(port)),
                    NetworkPolicyPort(protocol="UDP", port=IntOrString(port)),
                ],
                to=[NetworkPolicyPeer(namespace_selector=LabelSelector.make())],
            )
        ],
    )


def allow_no_ingress_nor_egress(ns: str, to_labels: Dict[str, str]) -> NetworkPolicy:
    return _policy("allow-nothing", ns, _sel(to_labels), ["Ingress", "Egress"])


# kube-docs accidental and/or (kubedocs.go)
def accidental_and(
    ns: str,
    target_labels: Dict[str, str],
    ingress_ns_labels: Dict[str, str],
    ingress_pod_labels: Dict[str, str],
) -> NetworkPolicy:
    """ONE peer with both selectors: namespace AND pod must match."""
    return _policy(
        "accidental-and",
        ns,
        _sel(target_labels),
        ["Ingress"],
        ingress=[
            NetworkPolicyIngressRule(
                from_=[
                    NetworkPolicyPeer(
                        namespace_selector=_sel(ingress_ns_labels),
                        pod_selector=_sel(ingress_pod_labels),
                    )
                ]
            )
        ],
    )


def accidental_or(
    ns: str,
    target_labels: Dict[str, str],
    ingress_ns_labels: Dict[str, str],
    ingress_pod_labels: Dict[str, str],
) -> NetworkPolicy:
    """TWO peers: namespace-selector peer OR pod-selector peer."""
    return _policy(
        "accidental-or",
        ns,
        _sel(target_labels),
        ["Ingress"],
        ingress=[
            NetworkPolicyIngressRule(
                from_=[
                    NetworkPolicyPeer(namespace_selector=_sel(ingress_ns_labels)),
                    NetworkPolicyPeer(pod_selector=_sel(ingress_pod_labels)),
                ]
            )
        ],
    )


def all_examples() -> List[NetworkPolicy]:
    """policies.go:699-728."""
    return [
        allow_nothing_to("default", {"app": "web"}),
        allow_nothing_to_empty_ingress("default", {"all": "web"}),
        allow_from_to(
            "default", {"app": "bookstore"}, {"app": "bookstore", "role": "api"}
        ),
        allow_all_to("default", {"app": "web"}),
        allow_nothing_to_anything("default"),
        allow_all_within_namespace("default"),
        accidental_and("default", {"a": "b"}, {"user": "alice"}, {"role": "client"}),
        accidental_or("default", {"a": "b"}, {"user": "alice"}, {"role": "client"}),
        allow_all_to_version2("default", {"app": "web"}),
        allow_all_to_version3("default", {"app": "web"}),
        allow_all_to_version4("default", {"app": "web"}),
        allow_from_namespace_to("default", {"purpose": "production"}, {"app": "web"}),
        allow_from_different_namespace_with_labels_to(
            "default", {"type": "monitoring"}, {"team": "operations"}, {"app": "web"}
        ),
        allow_from_anywhere("default", {"app": "web"}),
        allow_specific_port_to(
            "default", {"role": "monitoring"}, {"app": "apiserver"}, 5000
        ),
        allow_from_multiple_to(
            "default",
            [
                {"app": "bookstore", "role": "search"},
                {"app": "bookstore", "role": "api"},
                {"app": "inventory", "role": "web"},
            ],
            {"app": "bookstore", "role": "db"},
        ),
        allow_no_egress_from_labels("default", {"app": "foo"}),
        allow_egress_on_port("default", {"app": "foo"}, 53),
        allow_no_egress_from_namespace("default"),
        allow_egress_to_all_namespaces_on_port("default", {"app": "foo"}, 53),
        allow_no_ingress_nor_egress("default", {"app": "foo"}),
    ]
