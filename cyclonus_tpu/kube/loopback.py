"""Loopback cluster: REAL-socket conformance backend without kubernetes.

The reference proves its real-cluster path with a KinD flow
(hack/kind/run-cyclonus.sh:1-60); this environment has no docker/kind/
kubectl and no netfilter, so that flow cannot run here.  This module is
the strongest available substitute — and a capability the reference
itself lacks: a cluster whose pods are real OS processes with dedicated
loopback IPs (the 127/8 block is fully bindable on Linux), whose probes
are real TCP connects / UDP datagrams issued by the real in-pod worker
subprocess, and whose NetworkPolicies are enforced per-connection by the
pod servers against a verdict map (kube/loopback_server.py).

What is REAL here, vs the in-process mock (ikubernetes.MockKubernetes +
mockcni): pod processes and lifecycle, socket binds on 80/81, source-IP
attribution (clients bind the source pod's address, servers enforce on
getpeername), unserved-port refusals from the kernel, UDP timeout
semantics, the worker's subprocess + JSON protocol, and probe
concurrency.  What is emulated: the allow/deny DECISION comes from this
framework's own matcher (as the perfect-CNI mock's does) because
userspace cannot install packet filters — so this backend validates the
probe/exec/worker/compare machinery end-to-end over a real network
stack, not an independent CNI implementation.

Used by `generate --loopback` / `probe --loopback` and
tests/test_loopback.py (incl. the journaled conflict-case conformance
run committed under artifacts/).
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import tempfile
import threading
from typing import Dict, List, Optional, Tuple

from .ikubernetes import KubeError, MockKubernetes
from .objects import KubePod

_ACK = b"A"
_INSTANCES = [0]


def native_probe(
    host: str,
    port: int,
    protocol: str,
    source_ip: Optional[str] = None,
    timeout: float = 1.0,
) -> Optional[str]:
    """One real probe against a loopback pod server; None = allowed,
    otherwise a short error string (the agnhost-connect analog: any
    failure, including no app-level ACK, means blocked).  source_ip
    binds the client socket so the server's getpeername sees the probing
    POD, not a generic 127.0.0.1 — source-IP attribution is what makes
    per-(src, dst) policy enforcement real on loopback."""
    proto = protocol.upper()
    if proto == "TCP":
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    elif proto == "UDP":
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    else:
        return f"protocol {protocol} unsupported on loopback"
    try:
        s.settimeout(timeout)
        if source_ip:
            s.bind((source_ip, 0))
        if proto == "TCP":
            s.connect((host, port))
            data = s.recv(1)
        else:
            # connect() the UDP socket so the kernel filters datagrams
            # from any peer other than (host, port) — otherwise a stray
            # datagram on the bound port could flip a blocked verdict to
            # allowed.  Bonus: ICMP port-unreachable surfaces as
            # ECONNREFUSED instead of a 1 s timeout.
            s.connect((host, port))
            s.send(b"?")
            data = s.recv(1)
        return None if data == _ACK else "closed without ack"
    except socket.timeout:
        return "timeout"
    except OSError as e:
        return f"connect error: {e.strerror or e}"
    finally:
        s.close()


class LoopbackKubernetes(MockKubernetes):
    """MockKubernetes state machine + real pod processes and probes.

    Pods get unique 127.x.y.z addresses; create_pod spawns one
    loopback_server process per pod (READY-handshaked) serving its
    TCP/UDP container ports; every state mutation that can change a
    verdict atomically rewrites the shared allow map the servers
    consult.  execute_remote_command performs the REAL probe instead of
    answering from a table: agnhost-style commands run native_probe
    bound to the source pod's IP, and /worker batches run the actual
    `python -m cyclonus_tpu.worker` subprocess with native connects.
    """

    def __init__(self, ready_timeout_s: float = 20.0):
        super().__init__(pass_rate=1.0)
        from .mockcni import PolicyAwareMockExec

        # base octet: unique per (process, instance) so parallel clusters
        # never collide on (ip, port) binds
        _INSTANCES[0] += 1
        self._base = 10 + (os.getpid() * 7 + _INSTANCES[0]) % 200
        self._ready_timeout_s = ready_timeout_s
        self._servers: Dict[Tuple[str, str], subprocess.Popen] = {}  # guarded-by: self._lock
        self._lock = threading.Lock()
        self._tmp = tempfile.mkdtemp(prefix="cyclonus-loopback-")
        self.verdict_path = os.path.join(self._tmp, "verdicts.json")
        # the same oracle the perfect-CNI mock uses, reused for the
        # verdict map + service-name resolution (kube/mockcni.py)
        self._oracle = PolicyAwareMockExec(self)
        self._write_verdicts()
        # pod servers are real child processes: they survive a parent
        # crash (unlike threads) and would hold their 127.x binds forever.
        # weakref.finalize (not atexit.register(self.close)) so a closed/
        # collected cluster doesn't stay pinned in the atexit table for
        # the process lifetime; close() detaches it.
        import weakref

        self._finalizer = weakref.finalize(
            self, _kill_servers, self._servers, self._lock, self._tmp
        )

    # --- pod lifecycle: real processes ---

    def _alloc_ip(self) -> str:
        i = self._pod_id  # MockKubernetes counter, already advanced
        return f"127.{self._base}.{i // 250}.{i % 250 + 1}"

    def create_pod(self, pod: KubePod) -> KubePod:
        pod = super().create_pod(pod)
        pod.pod_ip = self._alloc_ip()
        listens = [
            f"{p.protocol}:{p.container_port}"
            for c in pod.containers
            for p in c.ports
            if p.protocol in ("TCP", "UDP")
        ]
        if not listens:
            self._write_verdicts()
            return pod
        cmd = [
            sys.executable,
            "-m",
            "cyclonus_tpu.kube.loopback_server",
            "--ip",
            pod.pod_ip,
            "--verdicts",
            self.verdict_path,
        ]
        for spec in listens:
            cmd += ["--listen", spec]
        proc = subprocess.Popen(
            cmd,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
        )
        ready = _read_line_bounded(proc.stdout, self._ready_timeout_s)
        if ready.strip() != "READY":
            err = ""
            try:
                proc.kill()
                err = (proc.stderr.read() or "")[:500]
            except Exception:
                pass
            super().delete_pod(pod.namespace, pod.name)
            raise KubeError(
                f"loopback pod server for {pod.namespace}/{pod.name} "
                f"failed to start: {err or 'no READY within timeout'}"
            )
        with self._lock:
            self._servers[(pod.namespace, pod.name)] = proc
        self._write_verdicts()
        return pod

    def delete_pod(self, namespace: str, pod: str) -> None:
        super().delete_pod(namespace, pod)
        with self._lock:
            proc = self._servers.pop((namespace, pod), None)
        if proc is not None:
            proc.kill()
            proc.wait(timeout=5)
        self._write_verdicts()

    def delete_namespace(self, namespace: str) -> None:
        pods = [p.name for p in self.get_pods_in_namespace(namespace)]
        super().delete_namespace(namespace)
        for name in pods:
            with self._lock:
                proc = self._servers.pop((namespace, name), None)
            if proc is not None:
                proc.kill()
                proc.wait(timeout=5)
        self._write_verdicts()

    def close(self) -> None:
        """Kill every pod server and drop the verdict dir (idempotent:
        the finalizer runs its callback at most once — whether called
        here, at GC, or at interpreter exit)."""
        self._finalizer()

    def __enter__(self) -> "LoopbackKubernetes":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # --- verdict map: every policy-relevant mutation rewrites it ---

    def _write_verdicts(self) -> None:
        allow: List[str] = []
        pods = [
            (ns_name, pod)
            for ns_name, ns in self.namespaces.items()
            for pod in ns.pods.values()
        ]
        for src_ns, src in pods:
            for dst_ns, dst in pods:
                for c in dst.containers:
                    for p in c.ports:
                        if p.protocol not in ("TCP", "UDP"):
                            continue
                        if self._oracle._verdict_resolved(
                            src_ns, src, dst_ns, dst, p.container_port, p.protocol
                        ):
                            allow.append(
                                f"{src.pod_ip}|{dst.pod_ip}|"
                                f"{p.container_port}|{p.protocol}"
                            )
        tmp = self.verdict_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"allow": allow}, f)
        os.replace(tmp, self.verdict_path)  # atomic for per-probe reloads

    def _mutated(self):
        # the oracle's compiled-policy cache keys on policy_rev (bumped by
        # super()); labels/pods have no rev, so verdicts must recompute
        self._write_verdicts()

    def create_namespace(self, ns):
        out = super().create_namespace(ns)
        self._mutated()
        return out

    def set_namespace_labels(self, namespace, labels):
        out = super().set_namespace_labels(namespace, labels)
        self._mutated()
        return out

    def set_pod_labels(self, namespace, pod, labels):
        out = super().set_pod_labels(namespace, pod, labels)
        self._mutated()
        return out

    def create_network_policy(self, policy):
        out = super().create_network_policy(policy)
        self._mutated()
        return out

    def update_network_policy(self, policy):
        out = super().update_network_policy(policy)
        self._mutated()
        return out

    def delete_network_policy(self, namespace, name):
        super().delete_network_policy(namespace, name)
        self._mutated()

    def delete_all_network_policies_in_namespace(self, namespace):
        super().delete_all_network_policies_in_namespace(namespace)
        self._mutated()

    # --- exec: REAL probes ---

    def _resolve_host(self, host: str) -> str:
        """Service names / cluster IPs -> backing pod IP (there is no DNS
        on loopback); pod IPs pass through; unknown hosts pass through
        and fail at connect time, like a real missing DNS record."""
        dest = self._oracle._find_dest_pod(host)
        return dest[1].pod_ip if dest is not None else host

    def execute_remote_command(
        self, namespace: str, pod: str, container: str, command: List[str]
    ) -> Tuple[str, str, Optional[str]]:
        ns = self._ns(namespace)
        if pod not in ns.pods:
            raise KubeError(f"pod {namespace}/{pod} not found")
        pod_obj = ns.pods[pod]
        if not any(c.name == container for c in pod_obj.containers):
            raise KubeError(f"container {namespace}/{pod}/{container} not found")

        if command and command[0] == "/worker":
            # run the REAL in-pod batch prober as a real subprocess with
            # native connects bound to this pod's address
            from ..worker.model import Batch

            batch = Batch.from_json(command[command.index("--jobs") + 1])
            for req in batch.requests:
                req.host = self._resolve_host(req.host)
            env = dict(os.environ)
            env["CYCLONUS_CONNECT_NATIVE"] = "1"
            env["CYCLONUS_SOURCE_IP"] = pod_obj.pod_ip
            # worst case every probe runs the full 1s timeout twice
            # (retry) at worker concurrency 10; a batch that still
            # exceeds the bound reports a check failure instead of
            # crashing the run with an uncaught TimeoutExpired
            budget = 30 + (2.5 * len(batch.requests)) / 10
            try:
                proc = subprocess.run(
                    [
                        sys.executable,
                        "-m",
                        "cyclonus_tpu.worker",
                        "--jobs",
                        batch.to_json(),
                    ],
                    capture_output=True,
                    text=True,
                    timeout=budget,
                    env=env,
                    cwd=os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
                )
            except subprocess.TimeoutExpired:
                raise KubeError(
                    f"loopback worker batch in {namespace}/{pod} exceeded "
                    f"{budget:.0f}s ({len(batch.requests)} requests)"
                )
            if proc.returncode != 0:
                return (proc.stdout, proc.stderr, f"worker exit {proc.returncode}")
            return (proc.stdout, "", None)

        # /agnhost connect <host:port> --timeout=1s --protocol=<p>
        address = command[2]
        host, port_s = address.rsplit(":", 1)
        protocol = command[-1].split("=", 1)[1].upper()
        err = native_probe(
            self._resolve_host(host),
            int(port_s),
            protocol,
            source_ip=pod_obj.pod_ip,
        )
        return ("", "", err)


def _kill_servers(servers: Dict, lock: threading.Lock, tmp: str) -> None:
    """Finalizer body: must not reference the cluster object (a bound
    method would keep it alive in the finalizer registry).  Mutates the
    SHARED servers dict in place — delete_pod pops from the same one."""
    import shutil

    with lock:
        procs = list(servers.values())
        servers.clear()
    for proc in procs:
        try:
            proc.kill()
            proc.wait(timeout=5)
        except Exception:
            pass
    shutil.rmtree(tmp, ignore_errors=True)


def _read_line_bounded(stream, timeout_s: float) -> str:
    """readline() with a deadline (the stream has no timeout of its own)."""
    out: List[str] = []

    def read():
        out.append(stream.readline())

    t = threading.Thread(target=read, daemon=True)
    t.start()
    t.join(timeout_s)
    return out[0] if out else ""
