"""Standalone pod server for the loopback cluster (kube/loopback.py).

One OS process per pod — the loopback analog of a pod's containers.  It
binds the pod's dedicated 127.x.y.z address on every served
(port, protocol) and answers probes with an application-level ACK byte
iff the cluster's current verdict map allows the (source pod -> this
pod, port, protocol) flow:

  TCP: accept -> look up peer IP -> send b"A" if allowed, else close.
  UDP: recvfrom -> look up peer IP -> reply b"A" if allowed, else drop.

Enforcement is at the application layer because this environment offers
no netfilter (see docs/LOOPBACK.md); a blocked flow still completes the
TCP handshake but never receives the ACK, which the native prober
(loopback.native_probe) treats as blocked — mirroring how agnhost
treats a connect that produces no service response.  Probes to a port
the pod does not serve never reach this process at all: they get a real
ECONNREFUSED / UDP timeout from the kernel.

The verdict map is a JSON file ({"allow": ["src|dst|port|PROTO", ...]})
rewritten atomically by LoopbackKubernetes on every policy/label/pod
mutation; the server re-stats it per probe and reloads on change, so a
policy perturbation is visible to the very next probe with no wait.

Protocol note: only TCP and UDP are served — SCTP needs kernel support
python sockets don't portably offer (the reference's kind clusters
commonly lack it too, hack/kind/run-cyclonus.sh).
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import threading


class VerdictMap:
    """mtime-cached view of the cluster's allow map."""

    def __init__(self, path: str):
        self.path = path
        self._stamp = None  # guarded-by: self._lock
        self._allow = frozenset()  # guarded-by: self._lock
        self._lock = threading.Lock()

    def allowed(self, src_ip: str, dst_ip: str, port: int, proto: str) -> bool:
        with self._lock:
            try:
                st = os.stat(self.path)
                stamp = (st.st_mtime_ns, st.st_size)
                if stamp != self._stamp:
                    with open(self.path) as f:
                        self._allow = frozenset(json.load(f)["allow"])
                    self._stamp = stamp
            except (OSError, ValueError, KeyError):
                # unreadable/missing map: fail closed (deny)
                return False
            return f"{src_ip}|{dst_ip}|{port}|{proto}" in self._allow


def _serve_tcp(srv: socket.socket, ip: str, port: int, verdicts: VerdictMap) -> None:
    srv.listen(64)
    while True:
        conn, addr = srv.accept()
        try:
            if verdicts.allowed(addr[0], ip, port, "TCP"):
                conn.sendall(b"A")
        except OSError:
            pass
        finally:
            conn.close()


def _serve_udp(srv: socket.socket, ip: str, port: int, verdicts: VerdictMap) -> None:
    while True:
        _data, addr = srv.recvfrom(64)
        if verdicts.allowed(addr[0], ip, port, "UDP"):
            try:
                srv.sendto(b"A", addr)
            except OSError:
                pass


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="loopback-pod-server")
    parser.add_argument("--ip", required=True, help="pod loopback IP")
    parser.add_argument(
        "--listen",
        action="append",
        required=True,
        metavar="PROTO:PORT",
        help="served port, e.g. TCP:80 (repeatable)",
    )
    parser.add_argument("--verdicts", required=True, help="verdict map JSON path")
    args = parser.parse_args(argv)

    verdicts = VerdictMap(args.verdicts)
    # bind everything on the MAIN thread so a taken port / bad address
    # fails the readiness handshake instead of dying silently in a
    # daemon thread after READY
    listeners = []
    for spec in args.listen:
        proto, port_s = spec.split(":", 1)
        proto, port = proto.upper(), int(port_s)
        kind = {"TCP": socket.SOCK_STREAM, "UDP": socket.SOCK_DGRAM}.get(proto)
        if kind is None:
            print(f"unsupported protocol {proto}", file=sys.stderr)
            return 2
        srv = socket.socket(socket.AF_INET, kind)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((args.ip, port))
        serve = _serve_tcp if proto == "TCP" else _serve_udp
        listeners.append((serve, srv, port))
    for serve, srv, port in listeners:
        threading.Thread(
            target=serve, args=(srv, args.ip, port, verdicts), daemon=True
        ).start()

    print("READY", flush=True)  # all sockets bound and serving
    threading.Event().wait()  # serve forever; parent kills the process
    return 0


if __name__ == "__main__":
    sys.exit(main())
