"""A policy-aware exec hook for MockKubernetes: emulates a PERFECT CNI by
evaluating the mock cluster's own NetworkPolicies with the scalar oracle.

The reference's mock exec is pass-rate-random (ikubernetes.go:314-340), so
`generate --mock` always shows comparison noise.  Wiring this in instead
makes the full conformance loop meaningful clusterless: simulated tables
must equal mock-kube tables on every step, or the framework itself is
broken.

Handles both exec shapes the framework issues:
  * /agnhost connect <host:port> --timeout=1s --protocol=<p>
  * /worker --jobs <json-batch>   (the in-pod batch prober)
"""

from __future__ import annotations

import json
from typing import List, Optional, Tuple, Union

from ..matcher.builder import build_network_policies
from ..matcher.core import InternalPeer, Policy, Traffic, TrafficPeer
from .ikubernetes import MockKubernetes
from .objects import KubePod


class PolicyAwareMockExec:
    """Install via ``mock.exec_verdict_fn = PolicyAwareMockExec(mock)``."""

    def __init__(self, mock: MockKubernetes):
        self.mock = mock
        self._policy_cache: Optional[Tuple[int, Policy]] = None

    def _compiled_policy(self) -> Policy:
        """Compile the mock's policy set once per netpol revision."""
        rev = self.mock.policy_rev
        if self._policy_cache is None or self._policy_cache[0] != rev:
            policies = [
                pol
                for ns in self.mock.namespaces.values()
                for pol in ns.netpols.values()
            ]
            self._policy_cache = (rev, build_network_policies(True, policies))
        return self._policy_cache[1]

    def _find_dest_pod(self, host: str) -> Optional[Tuple[str, KubePod]]:
        """Resolve an agnhost target host: pod IP, service cluster IP, or
        qualified service name (s-<ns>-<name>.<ns>.svc.cluster.local)."""
        for ns_name, ns in self.mock.namespaces.items():
            for pod in ns.pods.values():
                if pod.pod_ip == host:
                    return ns_name, pod
        for ns_name, ns in self.mock.namespaces.items():
            for svc in ns.services.values():
                if host == f"{svc.name}.{svc.namespace}.svc.cluster.local" or (
                    svc.cluster_ip and host == svc.cluster_ip
                ):
                    for pod in ns.pods.values():
                        if all(
                            pod.labels.get(k) == v for k, v in svc.selector.items()
                        ):
                            return ns_name, pod
        return None

    def _verdict(self, namespace: str, pod: str, host: str, port: int, protocol: str) -> bool:
        dest = self._find_dest_pod(host)
        if dest is None:
            return False  # unreachable host
        dest_ns, dest_pod = dest
        return self._verdict_resolved(
            namespace, self.mock.get_pod(namespace, pod), dest_ns, dest_pod, port, protocol
        )

    def _verdict_resolved(
        self,
        src_ns: str,
        src_pod: KubePod,
        dest_ns: str,
        dest_pod: KubePod,
        port: int,
        protocol: str,
    ) -> bool:
        """Verdict with both endpoints already resolved — the loopback
        cluster's verdict-map rebuild iterates pod objects directly and
        must not pay _find_dest_pod's linear scan per pair."""
        # the port must actually be served on this protocol
        serving = any(
            p.container_port == port and p.protocol == protocol
            for c in dest_pod.containers
            for p in c.ports
        )
        if not serving:
            return False

        # resolve the traffic's port name from the (port, protocol) container
        # actually being hit — this matches the name the simulated job carries
        # for all-available probes.  (NB the framework's numbered-port
        # resolution wart — resources.py resolve_numbered_port ignores
        # protocol — can diverge here only for numbered-port probes on a
        # non-first protocol combined with named-port rules, which no
        # generated case produces.)
        port_name = ""
        for c in dest_pod.containers:
            for p in c.ports:
                if p.container_port == port and p.protocol == protocol:
                    port_name = p.name

        traffic = Traffic(
            source=TrafficPeer(
                internal=InternalPeer(
                    pod_labels=src_pod.labels,
                    namespace_labels=self.mock.get_namespace(src_ns).labels,
                    namespace=src_ns,
                ),
                ip=src_pod.pod_ip,
            ),
            destination=TrafficPeer(
                internal=InternalPeer(
                    pod_labels=dest_pod.labels,
                    namespace_labels=self.mock.get_namespace(dest_ns).labels,
                    namespace=dest_ns,
                ),
                ip=dest_pod.pod_ip,
            ),
            resolved_port=port,
            resolved_port_name=port_name,
            protocol=protocol,
        )
        return self._compiled_policy().is_traffic_allowed(traffic).is_allowed

    def __call__(
        self, namespace: str, pod: str, container: str, command: List[str]
    ) -> Union[bool, Tuple[str, str, Optional[str]]]:
        if command and command[0] == "/worker":
            # batch prober: answer with the worker's JSON result protocol
            from ..worker.model import Batch, Result

            batch = Batch.from_json(command[command.index("--jobs") + 1])
            results = []
            for req in batch.requests:
                ok = self._verdict(
                    namespace, pod, req.host, req.port, req.protocol.upper()
                )
                results.append(
                    Result(
                        request=req, output="", error="" if ok else "blocked"
                    ).to_dict()
                )
            return (json.dumps(results), "", None)

        # /agnhost connect host:port --timeout=1s --protocol=<p>
        address = command[2]
        host, port_str = address.rsplit(":", 1)
        protocol = command[-1].split("=", 1)[1].upper()
        return self._verdict(namespace, pod, host, int(port_str), protocol)
