"""Pathological and shared-selector fixtures (reference:
pkg/kube/netpol/pathological.go, basic.go, complicated.go).

These are the edge-case policy shapes the matcher layer must compile
correctly: empty-vs-absent rule lists, every pod/namespace-selector peer
combination, IPBlocks with excepts, and the kitchen-sink "complicated"
policy.  Shipped in the library (not buried in tests) so users porting
reference-based test suites find the same named fixtures.
"""

from __future__ import annotations

from typing import Dict, List

from .netpol import (
    IPBlock,
    IntOrString,
    LabelSelector,
    NetworkPolicy,
    NetworkPolicyEgressRule,
    NetworkPolicyIngressRule,
    NetworkPolicyPeer,
    NetworkPolicyPort,
    NetworkPolicySpec,
)

# --- shared labels / selectors (pathological.go:8-29) ---

LABELS_AB: Dict[str, str] = {"a": "b"}
LABELS_CD: Dict[str, str] = {"b": "d"}  # wart preserved: key is "b", not "c"
LABELS_EF: Dict[str, str] = {"e": "f"}
LABELS_GH: Dict[str, str] = {"g": "g"}  # wart preserved: value is "g", not "h"

SELECTOR_AB = LabelSelector.make(match_labels=LABELS_AB)
SELECTOR_CD = LabelSelector.make(match_labels=LABELS_CD)
SELECTOR_EF = LabelSelector.make(match_labels=LABELS_EF)
SELECTOR_GH = LabelSelector.make(match_labels=LABELS_GH)
SELECTOR_EMPTY = LabelSelector.make()

NAMESPACE = "pathological-namespace"

# --- ipblock fixtures (pathological.go:31-38) ---

IPBLOCK_10_0_0_1_24 = IPBlock.make("10.0.0.1/24", ["10.0.0.2/30"])
IPBLOCK_192_168_242_213_24 = IPBlock.make("192.168.242.213/24")


def _policy(name: str, types: List[str], ingress=None, egress=None) -> NetworkPolicy:
    return NetworkPolicy(
        name=name,
        namespace=NAMESPACE,
        spec=NetworkPolicySpec(
            pod_selector=SELECTOR_EMPTY,
            policy_types=types,
            ingress=ingress or [],
            egress=egress or [],
        ),
    )


# --- allow nothing (deny all; pathological.go:40-113).  The *_EMPTY_RULES
# variants mirror the reference's nil-vs-empty-list pairs; this model does
# not distinguish the two (both compile to deny), so they are equal
# fixtures with the reference's names preserved. ---

ALLOW_NO_INGRESS = _policy("allow-no-ingress", ["Ingress"])
ALLOW_NO_INGRESS_EMPTY_INGRESS = _policy(
    "allow-no-ingress-empty-ingress", ["Ingress"]
)
ALLOW_NO_EGRESS = _policy("allow-no-egress", ["Egress"])
ALLOW_NO_EGRESS_EMPTY_EGRESS = _policy("allow-no-egress-empty-egress", ["Egress"])
ALLOW_NO_INGRESS_ALLOW_NO_EGRESS = _policy(
    "allow-no-ingress-allow-no-egress", ["Egress", "Ingress"]
)
ALLOW_NO_INGRESS_ALLOW_NO_EGRESS_EMPTY = _policy(
    "allow-no-ingress-allow-no-egress-empty-egress-empty-ingress",
    ["Egress", "Ingress"],
)

# --- allow all (pathological.go:115-162) ---

ALLOW_ALL_INGRESS = _policy(
    "allow-all-ingress", ["Ingress"], ingress=[NetworkPolicyIngressRule()]
)
ALLOW_ALL_EGRESS = _policy(
    "allow-all-egress", ["Egress"], egress=[NetworkPolicyEgressRule()]
)
ALLOW_ALL_INGRESS_ALLOW_ALL_EGRESS = _policy(
    "allow-all-ingress-allow-all-egress",
    ["Egress", "Ingress"],
    ingress=[NetworkPolicyIngressRule()],
    egress=[NetworkPolicyEgressRule()],
)

ALL_PATHOLOGICAL_POLICIES: List[NetworkPolicy] = [
    ALLOW_NO_INGRESS,
    ALLOW_NO_INGRESS_EMPTY_INGRESS,
    ALLOW_NO_EGRESS,
    ALLOW_NO_EGRESS_EMPTY_EGRESS,
    ALLOW_NO_INGRESS_ALLOW_NO_EGRESS,
    ALLOW_NO_INGRESS_ALLOW_NO_EGRESS_EMPTY,
    ALLOW_ALL_INGRESS,
    ALLOW_ALL_EGRESS,
    ALLOW_ALL_INGRESS_ALLOW_ALL_EGRESS,
]

# --- peer combination fixtures (pathological.go:164-213): every
# pod-selector x namespace-selector shape, used by builder tests ---

ALLOW_ALL_PODS_IN_POLICY_NAMESPACE_PEER = NetworkPolicyPeer()
ALLOW_ALL_PODS_IN_ALL_NAMESPACES_PEER = NetworkPolicyPeer(
    namespace_selector=SELECTOR_EMPTY
)
ALLOW_ALL_PODS_IN_MATCHING_NAMESPACES_PEER = NetworkPolicyPeer(
    namespace_selector=SELECTOR_AB
)
ALLOW_ALL_PODS_IN_POLICY_NAMESPACE_PEER_EMPTY_POD_SELECTOR = NetworkPolicyPeer(
    pod_selector=SELECTOR_EMPTY
)
ALLOW_ALL_PODS_IN_ALL_NAMESPACES_PEER_EMPTY_POD_SELECTOR = NetworkPolicyPeer(
    pod_selector=SELECTOR_EMPTY, namespace_selector=SELECTOR_EMPTY
)
ALLOW_ALL_PODS_IN_MATCHING_NAMESPACES_PEER_EMPTY_POD_SELECTOR = NetworkPolicyPeer(
    pod_selector=SELECTOR_EMPTY, namespace_selector=SELECTOR_AB
)
ALLOW_MATCHING_PODS_IN_POLICY_NAMESPACE_PEER = NetworkPolicyPeer(
    pod_selector=SELECTOR_CD
)
ALLOW_MATCHING_PODS_IN_ALL_NAMESPACES_PEER = NetworkPolicyPeer(
    pod_selector=SELECTOR_EF, namespace_selector=SELECTOR_EMPTY
)
ALLOW_MATCHING_PODS_IN_MATCHING_NAMESPACES_PEER = NetworkPolicyPeer(
    pod_selector=SELECTOR_GH, namespace_selector=SELECTOR_AB
)
ALLOW_IPBLOCK_PEER = NetworkPolicyPeer(ip_block=IPBLOCK_10_0_0_1_24)

# --- port fixtures (pathological.go:215-225) ---

ALLOW_ALL_PORTS_ON_PROTOCOL = NetworkPolicyPort(protocol="SCTP")
ALLOW_NUMBERED_PORT_ON_PROTOCOL = NetworkPolicyPort(
    protocol="TCP", port=IntOrString(9001)
)
ALLOW_NAMED_PORT_ON_PROTOCOL = NetworkPolicyPort(
    protocol="UDP", port=IntOrString("hello")
)


# --- basic builders (basic.go) ---

def allow_nothing_from(namespace: str, selector: LabelSelector) -> NetworkPolicy:
    return NetworkPolicy(
        name=f"allow-nothing-from-{namespace}",
        namespace=namespace,
        spec=NetworkPolicySpec(pod_selector=selector, policy_types=["Egress"]),
    )


def allow_from_to_ns_labels(
    namespace: str, selector: LabelSelector, ns_labels: Dict[str, str]
) -> NetworkPolicy:
    from .examples import label_string

    return NetworkPolicy(
        name=f"allow-from-{namespace}-to-{label_string(ns_labels)}",
        namespace=namespace,
        spec=NetworkPolicySpec(
            pod_selector=selector,
            policy_types=["Egress"],
            egress=[
                NetworkPolicyEgressRule(
                    to=[
                        NetworkPolicyPeer(
                            namespace_selector=LabelSelector.make(
                                match_labels=ns_labels
                            )
                        )
                    ]
                )
            ],
        ),
    )


def allow_all_ingress_policy(namespace: str) -> NetworkPolicy:
    return NetworkPolicy(
        name=f"allow-all-to-{namespace}",
        namespace=namespace,
        spec=NetworkPolicySpec(
            pod_selector=SELECTOR_EMPTY,
            policy_types=["Ingress"],
            ingress=[NetworkPolicyIngressRule()],
        ),
    )


def allow_all_egress_policy(namespace: str) -> NetworkPolicy:
    return NetworkPolicy(
        name="allow-all",
        namespace=namespace,
        spec=NetworkPolicySpec(
            pod_selector=SELECTOR_EMPTY,
            policy_types=["Egress"],
            egress=[NetworkPolicyEgressRule()],
        ),
    )


# --- the kitchen-sink example (complicated.go) ---

def example_complicated_network_policy() -> NetworkPolicy:
    return NetworkPolicy(
        name="complicated",
        namespace="example-namespace",
        spec=NetworkPolicySpec(
            pod_selector=SELECTOR_EMPTY,
            policy_types=["Ingress"],
            ingress=[
                NetworkPolicyIngressRule(
                    ports=[
                        NetworkPolicyPort(protocol="TCP", port=IntOrString(p))
                        for p in (3333, 4444, 5555)
                    ],
                    from_=[
                        NetworkPolicyPeer(pod_selector=SELECTOR_EMPTY),
                        NetworkPolicyPeer(namespace_selector=SELECTOR_EMPTY),
                        NetworkPolicyPeer(
                            ip_block=IPBlock.make(
                                "10.0.0.0/16",
                                ["10.0.0.0/28", "10.0.0.64/28"],
                            )
                        ),
                    ],
                )
            ],
        ),
    )
