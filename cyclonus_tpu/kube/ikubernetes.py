"""The cluster interface and the in-memory fake cluster
(reference: pkg/kube/ikubernetes.go).

``IKubernetes`` is the process/cluster boundary: everything above it (probe
fan-out, interpreter, generator) is cluster-agnostic.  ``MockKubernetes`` is
the key integration fixture — it implements the full interface in memory with
deterministic pod IPs and a pass-rate-random exec stub, so the entire
conformance pipeline runs clusterless (`generate --mock`).

Differences from the reference, on purpose:
  * pod IPs are allocated over 192.168.0.0/16 instead of a single /24, so the
    mock scales to ~65k pods instead of 254 (ikubernetes.go:292-297 panics at
    255) — needed for TPU-scale synthetic benchmarks.
  * errors are raised as ``KubeError`` instead of returned.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Callable, Dict, List, Optional, Tuple

from .netpol import NetworkPolicy
from .objects import KubeNamespace, KubePod, KubeService


class KubeError(Exception):
    """Cluster-interaction failure (the reference's returned error)."""


class IKubernetes:
    """18-method cluster interface (ikubernetes.go:11-35)."""

    # namespaces
    def create_namespace(self, ns: KubeNamespace) -> KubeNamespace:
        raise NotImplementedError

    def get_namespace(self, namespace: str) -> KubeNamespace:
        raise NotImplementedError

    def set_namespace_labels(self, namespace: str, labels: Dict[str, str]) -> KubeNamespace:
        raise NotImplementedError

    def delete_namespace(self, namespace: str) -> None:
        raise NotImplementedError

    # network policies
    def create_network_policy(self, policy: NetworkPolicy) -> NetworkPolicy:
        raise NotImplementedError

    def get_network_policies_in_namespace(self, namespace: str) -> List[NetworkPolicy]:
        raise NotImplementedError

    def update_network_policy(self, policy: NetworkPolicy) -> NetworkPolicy:
        raise NotImplementedError

    def delete_network_policy(self, namespace: str, name: str) -> None:
        raise NotImplementedError

    def delete_all_network_policies_in_namespace(self, namespace: str) -> None:
        raise NotImplementedError

    # services
    def create_service(self, service: KubeService) -> KubeService:
        raise NotImplementedError

    def get_service(self, namespace: str, name: str) -> KubeService:
        raise NotImplementedError

    def delete_service(self, namespace: str, name: str) -> None:
        raise NotImplementedError

    def get_services_in_namespace(self, namespace: str) -> List[KubeService]:
        raise NotImplementedError

    # pods
    def create_pod(self, pod: KubePod) -> KubePod:
        raise NotImplementedError

    def get_pod(self, namespace: str, pod: str) -> KubePod:
        raise NotImplementedError

    def delete_pod(self, namespace: str, pod: str) -> None:
        raise NotImplementedError

    def set_pod_labels(self, namespace: str, pod: str, labels: Dict[str, str]) -> KubePod:
        raise NotImplementedError

    def get_pods_in_namespace(self, namespace: str) -> List[KubePod]:
        raise NotImplementedError

    # exec
    def execute_remote_command(
        self, namespace: str, pod: str, container: str, command: List[str]
    ) -> Tuple[str, str, Optional[str]]:
        """Returns (stdout, stderr, command_error).  command_error is None on
        success; a setup failure raises KubeError (mirroring the reference's
        two distinct error returns, ikubernetes.go:34)."""
        raise NotImplementedError


# module-level helpers (ikubernetes.go:37-81)

def get_network_policies_in_namespaces(
    kubernetes: IKubernetes, namespaces: List[str]
) -> List[NetworkPolicy]:
    out: List[NetworkPolicy] = []
    for ns in namespaces:
        out.extend(kubernetes.get_network_policies_in_namespace(ns))
    return out


def delete_all_network_policies_in_namespaces(
    kubernetes: IKubernetes, namespaces: List[str]
) -> None:
    for ns in namespaces:
        kubernetes.delete_all_network_policies_in_namespace(ns)


def get_pods_in_namespaces(
    kubernetes: IKubernetes, namespaces: List[str]
) -> List[KubePod]:
    out: List[KubePod] = []
    for ns in namespaces:
        out.extend(kubernetes.get_pods_in_namespace(ns))
    return out


def get_services_in_namespaces(
    kubernetes: IKubernetes, namespaces: List[str]
) -> List[KubeService]:
    out: List[KubeService] = []
    for ns in namespaces:
        out.extend(kubernetes.get_services_in_namespace(ns))
    return out


class MockNamespace:
    def __init__(self, obj: KubeNamespace):
        self.namespace_object = obj
        self.netpols: Dict[str, NetworkPolicy] = {}
        self.pods: Dict[str, KubePod] = {}
        self.services: Dict[str, KubeService] = {}


class MockKubernetes(IKubernetes):
    """In-memory fake cluster (ikubernetes.go:83-340)."""

    MAX_PODS = 65534  # 192.168.0.0/16 minus network/broadcast

    def __init__(self, pass_rate: float = 1.0, seed: Optional[int] = None):
        self.namespaces: Dict[str, MockNamespace] = {}
        self.pass_rate = pass_rate
        self._pod_id = 1
        self._service_id = 0
        self._rng = random.Random(seed)
        # bumped on every netpol mutation; lets policy-aware exec hooks
        # cache their compiled policy (see kube.mockcni)
        self.policy_rev = 0
        # Optional policy-aware exec hook with signature
        # (namespace, pod, container, command) -> bool (True = connect
        # succeeded) OR a full (stdout, stderr, command_error) tuple; when
        # set, exec verdicts come from it instead of pass_rate.
        self.exec_verdict_fn: Optional[Callable[[str, str, str, List[str]], object]] = None

    def _ns(self, namespace: str) -> MockNamespace:
        if namespace in self.namespaces:
            return self.namespaces[namespace]
        raise KubeError(f"namespace {namespace} not found")

    # namespaces

    def create_namespace(self, ns: KubeNamespace) -> KubeNamespace:
        if ns.name in self.namespaces:
            raise KubeError(f"namespace {ns.name} already present")
        self.namespaces[ns.name] = MockNamespace(ns)
        return ns

    def get_namespace(self, namespace: str) -> KubeNamespace:
        return self._ns(namespace).namespace_object

    def set_namespace_labels(self, namespace: str, labels: Dict[str, str]) -> KubeNamespace:
        obj = self.get_namespace(namespace)
        obj.labels = dict(labels)
        return obj

    def delete_namespace(self, namespace: str) -> None:
        ns = self._ns(namespace)
        # dropping a namespace drops its policies: policy-aware exec
        # hooks cache their compiled policy keyed on this rev (mockcni,
        # loopback) and would otherwise keep enforcing ghost policies
        if ns.netpols:
            self.policy_rev += 1
        del self.namespaces[namespace]

    # network policies

    def create_network_policy(self, policy: NetworkPolicy) -> NetworkPolicy:
        ns = self._ns(policy.namespace)
        if policy.name in ns.netpols:
            raise KubeError(
                f"network policy {policy.namespace}/{policy.name} already present"
            )
        ns.netpols[policy.name] = policy
        self.policy_rev += 1
        return policy

    def get_network_policies_in_namespace(self, namespace: str) -> List[NetworkPolicy]:
        return list(self._ns(namespace).netpols.values())

    def update_network_policy(self, policy: NetworkPolicy) -> NetworkPolicy:
        ns = self._ns(policy.namespace)
        if policy.name not in ns.netpols:
            raise KubeError(
                f"network policy {policy.namespace}/{policy.name} not found"
            )
        ns.netpols[policy.name] = policy
        self.policy_rev += 1
        return policy

    def delete_network_policy(self, namespace: str, name: str) -> None:
        ns = self._ns(namespace)
        if name not in ns.netpols:
            raise KubeError(f"network policy {namespace}/{name} not found")
        del ns.netpols[name]
        self.policy_rev += 1

    def delete_all_network_policies_in_namespace(self, namespace: str) -> None:
        self._ns(namespace).netpols = {}
        self.policy_rev += 1

    # services

    def create_service(self, service: KubeService) -> KubeService:
        ns = self._ns(service.namespace)
        if service.name in ns.services:
            raise KubeError(
                f"service {service.namespace}/{service.name} already present"
            )
        if not service.cluster_ip:
            # a real apiserver allocates a ClusterIP on a COPY — the
            # caller's object must not mutate (a re-submit of the same
            # object would otherwise carry the stale IP)
            self._service_id += 1
            service = dataclasses.replace(
                service,
                cluster_ip=(
                    f"10.96.{self._service_id // 256}.{self._service_id % 256}"
                ),
            )
        ns.services[service.name] = service
        return service

    def get_service(self, namespace: str, name: str) -> KubeService:
        ns = self._ns(namespace)
        if name in ns.services:
            return ns.services[name]
        raise KubeError(f"service {namespace}/{name} not found")

    def delete_service(self, namespace: str, name: str) -> None:
        ns = self._ns(namespace)
        if name not in ns.services:
            raise KubeError(f"service {namespace}/{name} not found")
        del ns.services[name]

    def get_services_in_namespace(self, namespace: str) -> List[KubeService]:
        return list(self._ns(namespace).services.values())

    # pods

    def create_pod(self, pod: KubePod) -> KubePod:
        ns = self._ns(pod.namespace)
        if pod.name in ns.pods:
            raise KubeError(f"pod {pod.namespace}/{pod.name} already exists")
        if self._pod_id > self.MAX_PODS:
            raise KubeError(f"unable to handle more than {self.MAX_PODS} pods in mock")
        pod.phase = "Running"
        pod.pod_ip = f"192.168.{self._pod_id // 256}.{self._pod_id % 256}"
        self._pod_id += 1
        ns.pods[pod.name] = pod
        return pod

    def get_pod(self, namespace: str, pod: str) -> KubePod:
        ns = self._ns(namespace)
        if pod in ns.pods:
            return ns.pods[pod]
        raise KubeError(f"pod {namespace}/{pod} not found")

    def delete_pod(self, namespace: str, pod: str) -> None:
        ns = self._ns(namespace)
        if pod not in ns.pods:
            raise KubeError(f"pod {namespace}/{pod} not found")
        del ns.pods[pod]

    def set_pod_labels(self, namespace: str, pod: str, labels: Dict[str, str]) -> KubePod:
        obj = self.get_pod(namespace, pod)
        obj.labels = dict(labels)
        return obj

    def get_pods_in_namespace(self, namespace: str) -> List[KubePod]:
        return list(self._ns(namespace).pods.values())

    # cluster-wide reads (on the concrete backends, not IKubernetes,
    # mirroring the reference where GetAllNamespaces lives on
    # kube.Kubernetes rather than the interface — kubernetes.go)

    def get_all_namespaces(self) -> List[KubeNamespace]:
        return [m.namespace_object for m in self.namespaces.values()]

    def get_pods_all_namespaces(self) -> List[KubePod]:
        return [p for m in self.namespaces.values() for p in m.pods.values()]

    # exec

    def execute_remote_command(
        self, namespace: str, pod: str, container: str, command: List[str]
    ) -> Tuple[str, str, Optional[str]]:
        ns = self._ns(namespace)
        if pod not in ns.pods:
            raise KubeError(f"pod {namespace}/{pod} not found")
        pod_obj = ns.pods[pod]
        if not any(c.name == container for c in pod_obj.containers):
            raise KubeError(f"container {namespace}/{pod}/{container} not found")
        if self.exec_verdict_fn is not None:
            verdict = self.exec_verdict_fn(namespace, pod, container, command)
            if isinstance(verdict, tuple):
                # hook speaks the full (stdout, stderr, command_error)
                # protocol (e.g. the /worker batch prober)
                return verdict
            return ("", "", None if verdict else "mock verdict: blocked")
        if self._rng.random() > self.pass_rate:
            return ("", "", "mock call randomly failed")
        return ("", "", None)
