"""Real-cluster IKubernetes backend over the kubectl CLI (the reference's
process/cluster boundary is client-go + SPDY exec, kubernetes.go:182-218;
ours shells out to kubectl, which is equivalent for every operation the
framework performs and keeps the core dependency-free).

Requires kubectl on PATH and a reachable cluster; construction raises
KubeError otherwise.  Untested in CI (no cluster); the MockKubernetes path
covers all callers."""

from __future__ import annotations

import json
import shutil
import subprocess
from typing import Dict, List, Optional, Tuple

from ..images import AGNHOST_IMAGE
from .ikubernetes import IKubernetes, KubeError
from .netpol import NetworkPolicy
from .objects import (
    KubeContainer,
    KubeContainerPort,
    KubeNamespace,
    KubePod,
    KubeService,
    KubeServicePort,
)
from .yaml_io import parse_policy_dict, policy_to_dict


class KubectlKubernetes(IKubernetes):
    def __init__(self, context: str = ""):
        if shutil.which("kubectl") is None:
            raise KubeError("kubectl not found on PATH")
        self.context = context

    def _base(self) -> List[str]:
        cmd = ["kubectl"]
        if self.context:
            cmd += ["--context", self.context]
        return cmd

    def _run(self, args: List[str], input_text: Optional[str] = None) -> str:
        proc = subprocess.run(
            self._base() + args,
            # always give kubectl a CLOSED stdin ("" = empty pipe): with
            # an inherited never-closing fd 0 (CI runners, nohup), any
            # kubectl invocation that reads stdin would hang to timeout
            input=input_text if input_text is not None else "",
            capture_output=True,
            text=True,
            timeout=120,
        )
        if proc.returncode != 0:
            raise KubeError(
                f"kubectl {' '.join(args)} failed: {proc.stderr.strip()}"
            )
        return proc.stdout

    def _get_json(self, args: List[str]) -> dict:
        return json.loads(self._run(args + ["-o", "json"]))

    def _apply(self, manifest: dict) -> None:
        self._run(["apply", "-f", "-"], input_text=json.dumps(manifest))

    # namespaces

    def create_namespace(self, ns: KubeNamespace) -> KubeNamespace:
        self._apply(
            {
                "apiVersion": "v1",
                "kind": "Namespace",
                "metadata": {"name": ns.name, "labels": ns.labels},
            }
        )
        return ns

    def get_namespace(self, namespace: str) -> KubeNamespace:
        d = self._get_json(["get", "namespace", namespace])
        return KubeNamespace(
            name=d["metadata"]["name"], labels=d["metadata"].get("labels") or {}
        )

    def set_namespace_labels(self, namespace: str, labels: Dict[str, str]) -> KubeNamespace:
        current = self.get_namespace(namespace)
        patch = {"metadata": {"labels": {k: None for k in current.labels}}}
        patch["metadata"]["labels"].update(labels)
        self._run(
            ["patch", "namespace", namespace, "--type=merge", "-p", json.dumps(patch)]
        )
        return KubeNamespace(name=namespace, labels=dict(labels))

    def delete_namespace(self, namespace: str) -> None:
        self._run(["delete", "namespace", namespace, "--wait=true"])

    # network policies

    def create_network_policy(self, policy: NetworkPolicy) -> NetworkPolicy:
        self._apply(policy_to_dict(policy))
        return policy

    def get_network_policies_in_namespace(self, namespace: str) -> List[NetworkPolicy]:
        d = self._get_json(["get", "networkpolicy", "-n", namespace])
        return [parse_policy_dict(item) for item in d.get("items", [])]

    def get_network_policies_all_namespaces(self) -> List[NetworkPolicy]:
        """analyze --all-namespaces (reference analyze.go AllNamespaces /
        kubectl -A)."""
        d = self._get_json(["get", "networkpolicy", "--all-namespaces"])
        return [parse_policy_dict(item) for item in d.get("items", [])]

    def update_network_policy(self, policy: NetworkPolicy) -> NetworkPolicy:
        self._apply(policy_to_dict(policy))
        return policy

    def delete_network_policy(self, namespace: str, name: str) -> None:
        self._run(["delete", "networkpolicy", name, "-n", namespace])

    def delete_all_network_policies_in_namespace(self, namespace: str) -> None:
        self._run(["delete", "networkpolicy", "--all", "-n", namespace])

    # services

    def create_service(self, service: KubeService) -> KubeService:
        self._apply(
            {
                "apiVersion": "v1",
                "kind": "Service",
                "metadata": {"name": service.name, "namespace": service.namespace},
                "spec": {
                    "selector": service.selector,
                    "ports": [
                        {"name": p.name, "port": p.port, "protocol": p.protocol}
                        for p in service.ports
                    ],
                },
            }
        )
        return service

    def get_service(self, namespace: str, name: str) -> KubeService:
        d = self._get_json(["get", "service", name, "-n", namespace])
        spec = d.get("spec", {})
        return KubeService(
            namespace=namespace,
            name=name,
            selector=spec.get("selector") or {},
            ports=[
                KubeServicePort(
                    port=p["port"],
                    name=p.get("name", ""),
                    protocol=p.get("protocol", "TCP"),
                )
                for p in spec.get("ports", [])
            ],
            cluster_ip=spec.get("clusterIP", ""),
        )

    def delete_service(self, namespace: str, name: str) -> None:
        self._run(["delete", "service", name, "-n", namespace])

    def get_services_in_namespace(self, namespace: str) -> List[KubeService]:
        d = self._get_json(["get", "service", "-n", namespace])
        return [
            self.get_service(namespace, item["metadata"]["name"])
            for item in d.get("items", [])
        ]

    # pods

    def create_pod(self, pod: KubePod) -> KubePod:
        self._apply(
            {
                "apiVersion": "v1",
                "kind": "Pod",
                "metadata": {
                    "name": pod.name,
                    "namespace": pod.namespace,
                    "labels": pod.labels,
                },
                "spec": {
                    "terminationGracePeriodSeconds": 0,
                    "containers": [
                        _container_manifest(c) for c in pod.containers
                    ],
                },
            }
        )
        return pod

    def get_pod(self, namespace: str, pod: str) -> KubePod:
        d = self._get_json(["get", "pod", pod, "-n", namespace])
        return _pod_from_json(d)

    def delete_pod(self, namespace: str, pod: str) -> None:
        self._run(["delete", "pod", pod, "-n", namespace, "--wait=false"])

    def set_pod_labels(self, namespace: str, pod: str, labels: Dict[str, str]) -> KubePod:
        current = self.get_pod(namespace, pod)
        patch = {"metadata": {"labels": {k: None for k in current.labels}}}
        patch["metadata"]["labels"].update(labels)
        self._run(
            ["patch", "pod", pod, "-n", namespace, "--type=merge", "-p", json.dumps(patch)]
        )
        current.labels = dict(labels)
        return current

    def get_pods_in_namespace(self, namespace: str) -> List[KubePod]:
        d = self._get_json(["get", "pods", "-n", namespace])
        return [_pod_from_json(item) for item in d.get("items", [])]

    # cluster-wide reads (concrete-backend methods like the reference's
    # kube.Kubernetes.GetAllNamespaces, kubernetes.go)

    def get_all_namespaces(self) -> List[KubeNamespace]:
        d = self._get_json(["get", "namespaces"])
        return [
            KubeNamespace(
                name=item["metadata"]["name"],
                labels=item["metadata"].get("labels") or {},
            )
            for item in d.get("items", [])
        ]

    def get_pods_all_namespaces(self) -> List[KubePod]:
        d = self._get_json(["get", "pods", "--all-namespaces"])
        return [_pod_from_json(item) for item in d.get("items", [])]

    # exec

    def execute_remote_command(
        self, namespace: str, pod: str, container: str, command: List[str]
    ) -> Tuple[str, str, Optional[str]]:
        proc = subprocess.run(
            self._base()
            + ["exec", pod, "-c", container, "-n", namespace, "--"]
            + command,
            input="",  # closed stdin; see _run
            capture_output=True,
            text=True,
            timeout=60,
        )
        if proc.returncode != 0:
            return proc.stdout, proc.stderr, proc.stderr.strip() or "command failed"
        return proc.stdout, proc.stderr, None


def _container_manifest(c: KubeContainer) -> dict:
    port = c.ports[0] if c.ports else None
    manifest: dict = {
        "name": c.name,
        "imagePullPolicy": "IfNotPresent",
        "image": c.image or AGNHOST_IMAGE,
        "securityContext": {},
    }
    if port is not None:
        proto = port.protocol
        if proto == "TCP":
            manifest["command"] = [
                "/agnhost", "serve-hostname", "--tcp", "--http=false",
                "--port", str(port.container_port),
            ]
        elif proto == "UDP":
            manifest["command"] = [
                "/agnhost", "serve-hostname", "--udp", "--http=false",
                "--port", str(port.container_port),
            ]
        elif proto == "SCTP":
            manifest["env"] = [
                {"name": f"SERVE_SCTP_PORT_{port.container_port}", "value": "foo"}
            ]
            manifest["command"] = ["/agnhost", "porter"]
        manifest["ports"] = [
            {
                "containerPort": port.container_port,
                "name": port.name,
                "protocol": port.protocol,
            }
        ]
    return manifest


def _pod_from_json(d: dict) -> KubePod:
    containers = []
    for c in d.get("spec", {}).get("containers", []):
        containers.append(
            KubeContainer(
                name=c["name"],
                image=c.get("image", ""),
                ports=[
                    KubeContainerPort(
                        container_port=p["containerPort"],
                        name=p.get("name", ""),
                        protocol=p.get("protocol", "TCP"),
                    )
                    for p in c.get("ports", [])
                ],
            )
        )
    status = d.get("status", {})
    return KubePod(
        namespace=d["metadata"]["namespace"],
        name=d["metadata"]["name"],
        labels=d["metadata"].get("labels") or {},
        containers=containers,
        phase=status.get("phase", ""),
        pod_ip=status.get("podIP", ""),
    )
