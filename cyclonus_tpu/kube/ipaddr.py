"""IP / CIDR matching (reference: pkg/kube/ipaddress.go) plus the integer
encodings the tensor compiler uses (IPv4 as uint32 with prefix masks).

Go's net.ParseCIDR masks host bits (10.0.0.1/24 -> network 10.0.0.0/24);
ipaddress.ip_network(strict=False) does the same.
"""

from __future__ import annotations

import ipaddress
from typing import Optional, Tuple

from .netpol import IPBlock


def is_ip_in_cidr(ip: str, cidr: str) -> bool:
    """ipaddress.go:10-20.  Raises ValueError on malformed input (the
    reference returns an error which IPPeerMatcher.Allows panics on)."""
    try:
        net = ipaddress.ip_network(cidr, strict=False)
    except ValueError as e:
        raise ValueError(f"unable to parse CIDR '{cidr}': {e}") from e
    try:
        addr = ipaddress.ip_address(ip)
    except ValueError as e:
        raise ValueError(f"unable to parse IP '{ip}': {e}") from e
    # Go's net.IPNet.Contains normalizes IPv4-mapped IPv6 (::ffff:a.b.c.d)
    # to IPv4 via To4 before comparing; mirror that.  Other cross-family
    # combinations don't match.
    if addr.version == 6 and net.version == 4:
        mapped = addr.ipv4_mapped
        if mapped is None:
            return False
        addr = mapped
    elif addr.version != net.version:
        return False
    return addr in net


def is_ip_address_match_for_ip_block(ip: str, ip_block: IPBlock) -> bool:
    """CIDR minus excepts (ipaddress.go:22-40)."""
    if not is_ip_in_cidr(ip, ip_block.cidr):
        return False
    for except_cidr in ip_block.except_:
        if is_ip_in_cidr(ip, except_cidr):
            return False
    return True


def make_ipv4_cidr(ip: str, bits: int) -> str:
    """Mask an IPv4 address down to /bits (ipaddress.go:42-46); used by the
    generator to derive ipblock cases from a live pod IP."""
    addr = ipaddress.ip_address(ip)
    net = ipaddress.ip_network(f"{addr}/{bits}", strict=False)
    return f"{net.network_address}/{bits}"


def ip_to_uint32(ip: str) -> Optional[int]:
    """IPv4 address as uint32 for the tensor encoding; IPv4-mapped IPv6
    (::ffff:a.b.c.d) normalizes to its IPv4 form like Go's To4 (and
    is_ip_in_cidr above); None for other non-IPv4 and unparseable input."""
    try:
        addr = ipaddress.ip_address(ip)
    except ValueError:
        return None
    if addr.version == 6:
        mapped = addr.ipv4_mapped
        if mapped is None:
            return None
        addr = mapped
    return int(addr)


def cidr_to_base_and_prefix(cidr: str) -> Optional[Tuple[int, int]]:
    """IPv4 CIDR as (network-base uint32, prefix length); None for IPv6."""
    net = ipaddress.ip_network(cidr, strict=False)
    if net.version != 4:
        return None
    return int(net.network_address), net.prefixlen
