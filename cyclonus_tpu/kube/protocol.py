"""Protocol and service helpers (reference: pkg/kube/protocol.go, service.go)."""

from __future__ import annotations

from .netpol import PROTOCOL_SCTP, PROTOCOL_TCP, PROTOCOL_UDP


def parse_protocol(s: str) -> str:
    """protocol.go:8-18 (case-sensitive, raises on anything else)."""
    if s in (PROTOCOL_TCP, PROTOCOL_UDP, PROTOCOL_SCTP):
        return s
    raise ValueError(f"invalid protocol {s!r}")


def qualified_service_address(service_name: str, namespace: str) -> str:
    """service.go:9-11."""
    return f"{service_name}.{namespace}.svc.cluster.local"
