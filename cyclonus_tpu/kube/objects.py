"""Lightweight stand-ins for the k8s core/v1 objects the framework touches
(Namespace, Pod, Container, Service).  Only the fields the reference reads or
writes are modeled (see pkg/connectivity/probe/pod.go KubePod/KubeService)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class KubeContainerPort:
    container_port: int
    name: str = ""
    protocol: str = "TCP"


@dataclass
class KubeContainer:
    name: str
    ports: List[KubeContainerPort] = field(default_factory=list)
    image: str = ""


@dataclass
class KubePod:
    namespace: str
    name: str
    labels: Dict[str, str] = field(default_factory=dict)
    containers: List[KubeContainer] = field(default_factory=list)
    phase: str = ""  # "Running" once scheduled
    pod_ip: str = ""


@dataclass
class KubeServicePort:
    port: int
    name: str = ""
    protocol: str = "TCP"


@dataclass
class KubeService:
    namespace: str
    name: str
    selector: Dict[str, str] = field(default_factory=dict)
    ports: List[KubeServicePort] = field(default_factory=list)
    cluster_ip: str = ""


@dataclass
class KubeNamespace:
    name: str
    labels: Dict[str, str] = field(default_factory=dict)
