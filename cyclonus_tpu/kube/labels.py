"""Label-selector matching semantics (reference: pkg/kube/labelselector.go).

Full matchLabels + matchExpressions support with all four operators.  The
NotIn-with-absent-key rule (absent key => NO match, labelselector.go:37-49)
follows the k8s docs and is a known trap; it is covered by tests.
"""

from __future__ import annotations

import json
from typing import Dict

from .netpol import (
    LabelSelector,
    LabelSelectorRequirement,
    OP_DOES_NOT_EXIST,
    OP_EXISTS,
    OP_IN,
    OP_NOT_IN,
)


def is_name_match(object_name: str, matcher: str) -> bool:
    """Kube pattern: empty matcher matches all (labelselector.go:17-22)."""
    if matcher == "":
        return True
    return object_name == matcher


def is_match_expression_match(
    labels: Dict[str, str], exp: LabelSelectorRequirement
) -> bool:
    """One matchExpression against a label set (labelselector.go:24-59)."""
    if exp.operator == OP_IN:
        if exp.key not in labels:
            return False
        return labels[exp.key] in exp.values
    elif exp.operator == OP_NOT_IN:
        # Absent key => not a match, even for NotIn (k8s set-based requirement
        # docs; labelselector.go:37-49).
        if exp.key not in labels:
            return False
        return labels[exp.key] not in exp.values
    elif exp.operator == OP_EXISTS:
        return exp.key in labels
    elif exp.operator == OP_DOES_NOT_EXIST:
        return exp.key not in labels
    else:
        raise ValueError(f"invalid operator {exp.operator!r}")


def is_labels_match_label_selector(
    labels: Dict[str, str], selector: LabelSelector
) -> bool:
    """matchLabels and matchExpressions are ANDed; an empty selector matches
    all objects (labelselector.go:61-86)."""
    for key, val in selector.match_labels_items:
        if labels.get(key) != val:
            return False
    for exp in selector.match_expressions:
        if not is_match_expression_match(labels, exp):
            return False
    return True


def is_label_selector_empty(selector: LabelSelector) -> bool:
    return len(selector.match_labels_items) == 0 and len(selector.match_expressions) == 0


def serialize_label_selector(selector: LabelSelector) -> str:
    """Deterministic string form used in primary keys
    (labelselector.go:92-112)."""
    key_vals = [f"{k}: {v}" for k, v in selector.match_labels_items]
    exprs = [
        {"key": e.key, "operator": e.operator, "values": list(e.values)}
        for e in selector.match_expressions
    ]
    return json.dumps(
        ["MatchLabels", key_vals, "MatchExpression", exprs], separators=(",", ":")
    )


def label_selector_table_lines(selector: LabelSelector) -> str:
    """Human-readable selector rendering (labelselector.go:114-132)."""
    if is_label_selector_empty(selector):
        return "all pods"
    lines = []
    if selector.match_labels_items:
        lines.append("Match labels:")
        for key, val in selector.match_labels_items:
            lines.append(f"  {key}: {val}")
    if selector.match_expressions:
        lines.append("Match expressions:")
        for exp in selector.match_expressions:
            lines.append(f"  {exp.key} {exp.operator} {list(exp.values)}")
    return "\n".join(lines)
