"""k8s object model and primitives (reference: pkg/kube).

No kubernetes client dependency for the core: policies, selectors, and IP
blocks are plain dataclasses, so the whole engine runs clusterless.
"""

from .netpol import (
    IntOrString,
    LabelSelector,
    LabelSelectorRequirement,
    IPBlock,
    NetworkPolicyPort,
    NetworkPolicyPeer,
    NetworkPolicyIngressRule,
    NetworkPolicyEgressRule,
    NetworkPolicySpec,
    NetworkPolicy,
    PROTOCOL_TCP,
    PROTOCOL_UDP,
    PROTOCOL_SCTP,
    POLICY_TYPE_INGRESS,
    POLICY_TYPE_EGRESS,
)
from .labels import (
    is_name_match,
    is_match_expression_match,
    is_labels_match_label_selector,
    is_label_selector_empty,
    serialize_label_selector,
    label_selector_table_lines,
)
from .ipaddr import (
    is_ip_in_cidr,
    is_ip_address_match_for_ip_block,
    make_ipv4_cidr,
    ip_to_uint32,
    cidr_to_base_and_prefix,
)
from .yaml_io import (
    load_policies_from_path,
    parse_policy_dict,
    policy_to_dict,
    policies_to_yaml,
)
from .ikubernetes import IKubernetes, MockKubernetes, MockNamespace
from .protocol import parse_protocol, qualified_service_address

__all__ = [
    "IntOrString",
    "LabelSelector",
    "LabelSelectorRequirement",
    "IPBlock",
    "NetworkPolicyPort",
    "NetworkPolicyPeer",
    "NetworkPolicyIngressRule",
    "NetworkPolicyEgressRule",
    "NetworkPolicySpec",
    "NetworkPolicy",
    "PROTOCOL_TCP",
    "PROTOCOL_UDP",
    "PROTOCOL_SCTP",
    "POLICY_TYPE_INGRESS",
    "POLICY_TYPE_EGRESS",
    "is_name_match",
    "is_match_expression_match",
    "is_labels_match_label_selector",
    "is_label_selector_empty",
    "serialize_label_selector",
    "label_selector_table_lines",
    "is_ip_in_cidr",
    "is_ip_address_match_for_ip_block",
    "make_ipv4_cidr",
    "ip_to_uint32",
    "cidr_to_base_and_prefix",
    "load_policies_from_path",
    "parse_policy_dict",
    "policy_to_dict",
    "policies_to_yaml",
    "IKubernetes",
    "MockKubernetes",
    "MockNamespace",
    "parse_protocol",
    "qualified_service_address",
]
