"""Kubernetes NetworkPolicy object model as plain dataclasses.

Mirrors the subset of k8s.io/api types the reference consumes
(networkingv1.NetworkPolicy and friends; see reference pkg/matcher/builder.go),
without any kubernetes client dependency.  The nil-vs-empty distinctions that
carry semantic weight in the k8s API are preserved:

  * ``NetworkPolicyPeer.pod_selector`` / ``namespace_selector``: ``None`` vs
    empty selector mean different things (builder.go:115-142).
  * ``NetworkPolicyPort.port``: ``None`` means "all ports on this protocol"
    (portmatcher.go:26-39).
  * rule-level ``ports`` / ``peers`` empty means "all" (builder.go:79-88).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Union

PROTOCOL_TCP = "TCP"
PROTOCOL_UDP = "UDP"
PROTOCOL_SCTP = "SCTP"

POLICY_TYPE_INGRESS = "Ingress"
POLICY_TYPE_EGRESS = "Egress"

NAMESPACE_DEFAULT = "default"


class IntOrString:
    """k8s intstr.IntOrString: a value that is either an int port or a named port."""

    __slots__ = ("value",)

    def __init__(self, value: Union[int, str]):
        if isinstance(value, bool) or not isinstance(value, (int, str)):
            raise TypeError(f"IntOrString requires int or str, got {type(value)}")
        self.value = value

    @property
    def is_int(self) -> bool:
        return isinstance(self.value, int)

    @property
    def is_string(self) -> bool:
        return isinstance(self.value, str)

    @property
    def int_value(self) -> int:
        if not self.is_int:
            raise ValueError(f"not an int port: {self.value!r}")
        return self.value

    @property
    def str_value(self) -> str:
        if not self.is_string:
            raise ValueError(f"not a named port: {self.value!r}")
        return self.value

    def __eq__(self, other) -> bool:
        return isinstance(other, IntOrString) and self.value == other.value

    def __hash__(self) -> int:
        return hash((type(self.value) is int, self.value))

    def __repr__(self) -> str:
        return f"IntOrString({self.value!r})"


def port(value: Union[int, str]) -> IntOrString:
    """Convenience constructor for ports in tests and the generator DSL."""
    return IntOrString(value)


# Label selector operators (metav1.LabelSelectorOperator).
OP_IN = "In"
OP_NOT_IN = "NotIn"
OP_EXISTS = "Exists"
OP_DOES_NOT_EXIST = "DoesNotExist"


@dataclass(frozen=True)
class LabelSelectorRequirement:
    key: str
    operator: str
    values: tuple = ()

    def to_dict(self) -> dict:
        d = {"key": self.key, "operator": self.operator}
        if self.values:
            d["values"] = list(self.values)
        return d

    @staticmethod
    def from_dict(d: dict) -> "LabelSelectorRequirement":
        return LabelSelectorRequirement(
            key=d["key"], operator=d["operator"], values=tuple(d.get("values") or ())
        )


@dataclass(frozen=True)
class LabelSelector:
    """metav1.LabelSelector: matchLabels AND matchExpressions.

    Frozen/hashable so selectors can key dicts; match_labels is stored as a
    sorted tuple of (key, value) pairs internally but constructed from a dict.
    """

    match_labels_items: tuple = ()
    match_expressions: tuple = ()

    @staticmethod
    def make(
        match_labels: Optional[Dict[str, str]] = None,
        match_expressions: Optional[List[LabelSelectorRequirement]] = None,
    ) -> "LabelSelector":
        return LabelSelector(
            match_labels_items=tuple(sorted((match_labels or {}).items())),
            match_expressions=tuple(match_expressions or ()),
        )

    @property
    def match_labels(self) -> Dict[str, str]:
        return dict(self.match_labels_items)

    def to_dict(self) -> dict:
        d: dict = {}
        if self.match_labels_items:
            d["matchLabels"] = dict(self.match_labels_items)
        if self.match_expressions:
            d["matchExpressions"] = [e.to_dict() for e in self.match_expressions]
        return d

    @staticmethod
    def from_dict(d: Optional[dict]) -> Optional["LabelSelector"]:
        if d is None:
            return None
        return LabelSelector.make(
            match_labels=d.get("matchLabels") or {},
            match_expressions=[
                LabelSelectorRequirement.from_dict(e)
                for e in (d.get("matchExpressions") or [])
            ],
        )


# An empty selector ("match everything").
EMPTY_SELECTOR = LabelSelector.make()


@dataclass(frozen=True)
class IPBlock:
    cidr: str
    except_: tuple = ()  # tuple of CIDR strings

    @staticmethod
    def make(cidr: str, except_: Optional[List[str]] = None) -> "IPBlock":
        return IPBlock(cidr=cidr, except_=tuple(except_ or ()))

    def to_dict(self) -> dict:
        d: dict = {"cidr": self.cidr}
        if self.except_:
            d["except"] = list(self.except_)
        return d

    @staticmethod
    def from_dict(d: Optional[dict]) -> Optional["IPBlock"]:
        if d is None:
            return None
        return IPBlock.make(cidr=d["cidr"], except_=list(d.get("except") or []))


@dataclass
class NetworkPolicyPort:
    """networkingv1.NetworkPolicyPort. protocol None defaults to TCP at build
    time (builder.go:161-165); port None means all ports on the protocol."""

    protocol: Optional[str] = None
    port: Optional[IntOrString] = None
    end_port: Optional[int] = None

    def to_dict(self) -> dict:
        d: dict = {}
        if self.protocol is not None:
            d["protocol"] = self.protocol
        if self.port is not None:
            d["port"] = self.port.value
        if self.end_port is not None:
            d["endPort"] = self.end_port
        return d

    @staticmethod
    def from_dict(d: dict) -> "NetworkPolicyPort":
        p = d.get("port")
        return NetworkPolicyPort(
            protocol=d.get("protocol"),
            port=IntOrString(p) if p is not None else None,
            end_port=d.get("endPort"),
        )


@dataclass
class NetworkPolicyPeer:
    """networkingv1.NetworkPolicyPeer: exactly one of ip_block or
    (pod_selector and/or namespace_selector) may be set."""

    pod_selector: Optional[LabelSelector] = None
    namespace_selector: Optional[LabelSelector] = None
    ip_block: Optional[IPBlock] = None

    def to_dict(self) -> dict:
        d: dict = {}
        if self.pod_selector is not None:
            d["podSelector"] = self.pod_selector.to_dict()
        if self.namespace_selector is not None:
            d["namespaceSelector"] = self.namespace_selector.to_dict()
        if self.ip_block is not None:
            d["ipBlock"] = self.ip_block.to_dict()
        return d

    @staticmethod
    def from_dict(d: dict) -> "NetworkPolicyPeer":
        return NetworkPolicyPeer(
            pod_selector=LabelSelector.from_dict(d.get("podSelector")),
            namespace_selector=LabelSelector.from_dict(d.get("namespaceSelector")),
            ip_block=IPBlock.from_dict(d.get("ipBlock")),
        )


@dataclass
class NetworkPolicyIngressRule:
    ports: List[NetworkPolicyPort] = field(default_factory=list)
    from_: List[NetworkPolicyPeer] = field(default_factory=list)

    def to_dict(self) -> dict:
        d: dict = {}
        if self.ports:
            d["ports"] = [p.to_dict() for p in self.ports]
        if self.from_:
            d["from"] = [p.to_dict() for p in self.from_]
        return d

    @staticmethod
    def from_dict(d: dict) -> "NetworkPolicyIngressRule":
        return NetworkPolicyIngressRule(
            ports=[NetworkPolicyPort.from_dict(p) for p in (d.get("ports") or [])],
            from_=[NetworkPolicyPeer.from_dict(p) for p in (d.get("from") or [])],
        )


@dataclass
class NetworkPolicyEgressRule:
    ports: List[NetworkPolicyPort] = field(default_factory=list)
    to: List[NetworkPolicyPeer] = field(default_factory=list)

    def to_dict(self) -> dict:
        d: dict = {}
        if self.ports:
            d["ports"] = [p.to_dict() for p in self.ports]
        if self.to:
            d["to"] = [p.to_dict() for p in self.to]
        return d

    @staticmethod
    def from_dict(d: dict) -> "NetworkPolicyEgressRule":
        return NetworkPolicyEgressRule(
            ports=[NetworkPolicyPort.from_dict(p) for p in (d.get("ports") or [])],
            to=[NetworkPolicyPeer.from_dict(p) for p in (d.get("to") or [])],
        )


@dataclass
class NetworkPolicySpec:
    pod_selector: LabelSelector = EMPTY_SELECTOR
    policy_types: List[str] = field(default_factory=list)
    ingress: List[NetworkPolicyIngressRule] = field(default_factory=list)
    egress: List[NetworkPolicyEgressRule] = field(default_factory=list)

    def to_dict(self) -> dict:
        d: dict = {"podSelector": self.pod_selector.to_dict()}
        if self.policy_types:
            d["policyTypes"] = list(self.policy_types)
        if self.ingress:
            d["ingress"] = [r.to_dict() for r in self.ingress]
        if self.egress:
            d["egress"] = [r.to_dict() for r in self.egress]
        return d

    @staticmethod
    def from_dict(d: dict) -> "NetworkPolicySpec":
        return NetworkPolicySpec(
            pod_selector=LabelSelector.from_dict(d.get("podSelector")) or EMPTY_SELECTOR,
            policy_types=list(d.get("policyTypes") or []),
            ingress=[
                NetworkPolicyIngressRule.from_dict(r) for r in (d.get("ingress") or [])
            ],
            egress=[
                NetworkPolicyEgressRule.from_dict(r) for r in (d.get("egress") or [])
            ],
        )


@dataclass
class NetworkPolicy:
    name: str
    namespace: str = ""
    spec: NetworkPolicySpec = field(default_factory=NetworkPolicySpec)

    def effective_namespace(self) -> str:
        """Empty namespace defaults to 'default' (builder.go:28-33)."""
        return self.namespace if self.namespace else NAMESPACE_DEFAULT

    def copy(self) -> "NetworkPolicy":
        return dataclasses.replace(
            self,
            spec=NetworkPolicySpec.from_dict(self.spec.to_dict()),
        )
