"""Feature extraction over Netpol structure (reference: generator/feature.go):
~40 feature strings powering the per-feature pass/fail report."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

ACTION_FEATURE_CREATE_POLICY = "action: create policy"
ACTION_FEATURE_UPDATE_POLICY = "action: update policy"
ACTION_FEATURE_DELETE_POLICY = "action: delete policy"
ACTION_FEATURE_CREATE_NAMESPACE = "action: create namespace"
ACTION_FEATURE_SET_NAMESPACE_LABELS = "action: set namespace labels"
ACTION_FEATURE_DELETE_NAMESPACE = "action: delete namespace"
ACTION_FEATURE_READ_POLICIES = "action: read policies"
ACTION_FEATURE_CREATE_POD = "action: create pod"
ACTION_FEATURE_SET_POD_LABELS = "action: set pod labels"
ACTION_FEATURE_DELETE_POD = "action: delete pod"

POLICY_FEATURE_INGRESS = "policy with ingress"
POLICY_FEATURE_EGRESS = "policy with egress"
POLICY_FEATURE_INGRESS_AND_EGRESS = "policy with both ingress and egress"

TARGET_FEATURE_SPECIFIC_NAMESPACE = "target: specific namespace"
TARGET_FEATURE_NAMESPACE_EMPTY = "target: empty namespace"
TARGET_FEATURE_POD_SELECTOR_EMPTY = "target: empty pod selector"
TARGET_FEATURE_POD_SELECTOR_MATCH_LABELS = "target: pod selector match labels"
TARGET_FEATURE_POD_SELECTOR_MATCH_EXPRESSIONS = "target: pod selector match expression"

RULE_FEATURE_ALL_PEERS_ALL_PORTS = "all peers on all ports/protocols"
RULE_FEATURE_SLICE_EMPTY = "0 rules"
RULE_FEATURE_SLICE_SIZE_1 = "1 rule"
RULE_FEATURE_SLICE_SIZE_2_PLUS = "2+ rules"

PEER_FEATURE_PORT_SLICE_EMPTY = "0 port/protocols"
PEER_FEATURE_PORT_SLICE_SIZE_1 = "1 port/protocol"
PEER_FEATURE_PORT_SLICE_SIZE_2_PLUS = "2+ port/protocols"
PEER_FEATURE_NUMBERED_PORT = "numbered port"
PEER_FEATURE_NAMED_PORT = "named port"
PEER_FEATURE_NIL_PORT = "nil port"
PEER_FEATURE_NIL_PROTOCOL = "nil protocol"
PEER_FEATURE_TCP_PROTOCOL = "policy on TCP"
PEER_FEATURE_UDP_PROTOCOL = "policy on UDP"
PEER_FEATURE_SCTP_PROTOCOL = "policy on SCTP"

PEER_FEATURE_PEER_SLICE_EMPTY = "0 peers"
PEER_FEATURE_PEER_SLICE_SIZE_1 = "1 peer"
PEER_FEATURE_PEER_SLICE_SIZE_2_PLUS = "2+ peers"
PEER_FEATURE_IPBLOCK_EMPTY_EXCEPT = "IPBlock (no except)"
PEER_FEATURE_IPBLOCK_NONEMPTY_EXCEPT = "IPBlock with except"
PEER_FEATURE_POD_SELECTOR_NIL = "peer pod selector nil"
PEER_FEATURE_POD_SELECTOR_EMPTY = "peer pod selector empty"
PEER_FEATURE_POD_SELECTOR_MATCH_LABELS = "peer pod selector match labels"
PEER_FEATURE_POD_SELECTOR_MATCH_EXPRESSIONS = "peer pod selector match expression"
PEER_FEATURE_NAMESPACE_SELECTOR_NIL = "peer namespace selector nil"
PEER_FEATURE_NAMESPACE_SELECTOR_EMPTY = "peer namespace selector empty"
PEER_FEATURE_NAMESPACE_SELECTOR_MATCH_LABELS = "peer namespace selector match labels"
PEER_FEATURE_NAMESPACE_SELECTOR_MATCH_EXPRESSIONS = (
    "peer namespace selector match expression"
)


def _policy_features(policy, features: Dict[str, bool]) -> None:
    """feature.go:168-182."""
    has_ingress = policy.ingress is not None and len(policy.ingress.rules) > 0
    has_egress = policy.egress is not None and len(policy.egress.rules) > 0
    if has_ingress:
        features[POLICY_FEATURE_INGRESS] = True
    if has_egress:
        features[POLICY_FEATURE_EGRESS] = True
    if has_ingress and has_egress:
        features[POLICY_FEATURE_INGRESS_AND_EGRESS] = True


def _target_features(target, features: Dict[str, bool]) -> None:
    """feature.go:184-201."""
    if target.namespace == "":
        features[TARGET_FEATURE_NAMESPACE_EMPTY] = True
    else:
        features[TARGET_FEATURE_SPECIFIC_NAMESPACE] = True
    selector = target.pod_selector
    if not selector.match_labels_items and not selector.match_expressions:
        features[TARGET_FEATURE_POD_SELECTOR_EMPTY] = True
    if selector.match_labels_items:
        features[TARGET_FEATURE_POD_SELECTOR_MATCH_LABELS] = True
    if selector.match_expressions:
        features[TARGET_FEATURE_POD_SELECTOR_MATCH_EXPRESSIONS] = True


def _rules_features(peers, features: Dict[str, bool]) -> None:
    """feature.go:203-214."""
    n = len(peers.rules)
    if n == 0:
        features[RULE_FEATURE_SLICE_EMPTY] = True
    elif n == 1:
        features[RULE_FEATURE_SLICE_SIZE_1] = True
    else:
        features[RULE_FEATURE_SLICE_SIZE_2_PLUS] = True


def _rule_feature(rule, features: Dict[str, bool]) -> None:
    if len(rule.ports) == 0 and len(rule.peers) == 0:
        features[RULE_FEATURE_ALL_PEERS_ALL_PORTS] = True


def _peers_features(peers_list, features: Dict[str, bool]) -> None:
    n = len(peers_list)
    if n == 0:
        features[PEER_FEATURE_PEER_SLICE_EMPTY] = True
    elif n == 1:
        features[PEER_FEATURE_PEER_SLICE_SIZE_1] = True
    else:
        features[PEER_FEATURE_PEER_SLICE_SIZE_2_PLUS] = True


def _single_peer_feature(peer, features: Dict[str, bool]) -> None:
    """feature.go:233-270."""
    if peer.ip_block is not None:
        if not peer.ip_block.except_:
            features[PEER_FEATURE_IPBLOCK_EMPTY_EXCEPT] = True
        else:
            features[PEER_FEATURE_IPBLOCK_NONEMPTY_EXCEPT] = True
        return
    if peer.pod_selector is not None:
        sel = peer.pod_selector
        if not sel.match_labels_items and not sel.match_expressions:
            features[PEER_FEATURE_POD_SELECTOR_EMPTY] = True
        if sel.match_labels_items:
            features[PEER_FEATURE_POD_SELECTOR_MATCH_LABELS] = True
        if sel.match_expressions:
            features[PEER_FEATURE_POD_SELECTOR_MATCH_EXPRESSIONS] = True
    else:
        features[PEER_FEATURE_POD_SELECTOR_NIL] = True
    if peer.namespace_selector is not None:
        sel = peer.namespace_selector
        if not sel.match_labels_items and not sel.match_expressions:
            features[PEER_FEATURE_NAMESPACE_SELECTOR_EMPTY] = True
        if sel.match_labels_items:
            features[PEER_FEATURE_NAMESPACE_SELECTOR_MATCH_LABELS] = True
        if sel.match_expressions:
            features[PEER_FEATURE_NAMESPACE_SELECTOR_MATCH_EXPRESSIONS] = True
    else:
        features[PEER_FEATURE_NAMESPACE_SELECTOR_NIL] = True


def _ports_features(ports, features: Dict[str, bool]) -> None:
    n = len(ports)
    if n == 0:
        features[PEER_FEATURE_PORT_SLICE_EMPTY] = True
    elif n == 1:
        features[PEER_FEATURE_PORT_SLICE_SIZE_1] = True
    else:
        features[PEER_FEATURE_PORT_SLICE_SIZE_2_PLUS] = True


def _single_port_feature(port, features: Dict[str, bool]) -> None:
    """feature.go:283-308."""
    if port.port is None:
        features[PEER_FEATURE_NIL_PORT] = True
    elif port.port.is_int:
        features[PEER_FEATURE_NUMBERED_PORT] = True
    else:
        features[PEER_FEATURE_NAMED_PORT] = True
    if port.protocol is None:
        features[PEER_FEATURE_NIL_PROTOCOL] = True
    elif port.protocol == "TCP":
        features[PEER_FEATURE_TCP_PROTOCOL] = True
    elif port.protocol == "UDP":
        features[PEER_FEATURE_UDP_PROTOCOL] = True
    elif port.protocol == "SCTP":
        features[PEER_FEATURE_SCTP_PROTOCOL] = True


@dataclass
class NetpolTraverser:
    """feature.go:72-166: a visitor parameterized by hooks; traverse
    returns the feature set."""

    policy: Optional[Callable] = None
    target: Optional[Callable] = None
    direction: Optional[Callable] = None
    rule: Optional[Callable] = None
    peers: Optional[Callable] = None
    peer: Optional[Callable] = None
    ports: Optional[Callable] = None
    port: Optional[Callable] = None
    which: str = "both"  # "ingress" | "egress" | "both"

    def traverse(self, netpol) -> Dict[str, bool]:
        features: Dict[str, bool] = {}
        if self.policy is not None:
            self.policy(netpol, features)
        if self.target is not None:
            self.target(netpol.target, features)
        for is_ingress, peers in ((True, netpol.ingress), (False, netpol.egress)):
            if peers is None:
                continue
            if self.which == "ingress" and not is_ingress:
                continue
            if self.which == "egress" and is_ingress:
                continue
            if self.direction is not None:
                self.direction(peers, features)
            for rule in peers.rules:
                if self.rule is not None:
                    self.rule(rule, features)
                if self.peers is not None:
                    self.peers(rule.peers, features)
                if self.peer is not None:
                    for p in rule.peers:
                        self.peer(p, features)
                if self.ports is not None:
                    self.ports(rule.ports, features)
                if self.port is not None:
                    for p in rule.ports:
                        self.port(p, features)
        return features


GENERAL_TRAVERSER = NetpolTraverser(policy=_policy_features, target=_target_features)

INGRESS_TRAVERSER = NetpolTraverser(
    direction=_rules_features,
    rule=_rule_feature,
    peers=_peers_features,
    peer=_single_peer_feature,
    ports=_ports_features,
    port=_single_port_feature,
    which="ingress",
)

EGRESS_TRAVERSER = NetpolTraverser(
    direction=_rules_features,
    rule=_rule_feature,
    peers=_peers_features,
    peer=_single_peer_feature,
    ports=_ports_features,
    port=_single_port_feature,
    which="egress",
)
