"""Shared fixtures for the case families (reference: generator/constants.go)."""

from __future__ import annotations

from ..kube.netpol import (
    IntOrString,
    LabelSelector,
    LabelSelectorRequirement,
    NetworkPolicyPort,
    OP_IN,
)
from ..probe.probeconfig import (
    PROBE_MODE_SERVICE_NAME,
    ProbeConfig,
)

TCP = "TCP"
UDP = "UDP"
SCTP = "SCTP"

PORT53 = IntOrString(53)
PORT79 = IntOrString(79)
PORT80 = IntOrString(80)
PORT81 = IntOrString(81)
PORT82 = IntOrString(82)
PORT7981 = IntOrString(7981)

PORT_SERVE_79_TCP = IntOrString("serve-79-tcp")
PORT_SERVE_80_TCP = IntOrString("serve-80-tcp")
PORT_SERVE_81_TCP = IntOrString("serve-81-tcp")
PORT_SERVE_80_UDP = IntOrString("serve-80-udp")
PORT_SERVE_81_UDP = IntOrString("serve-81-udp")
PORT_SERVE_7981_UDP = IntOrString("serve-7981-udp")
PORT_SERVE_80_SCTP = IntOrString("serve-80-sctp")
PORT_SERVE_81_SCTP = IntOrString("serve-81-sctp")


def probe_all_available() -> ProbeConfig:
    return ProbeConfig.all_available_config(PROBE_MODE_SERVICE_NAME)


def probe_port(port: IntOrString, protocol: str) -> ProbeConfig:
    return ProbeConfig.port_protocol_config(port, protocol, PROBE_MODE_SERVICE_NAME)


EMPTY_SELECTOR = LabelSelector.make()
POD_A_MATCH_LABELS_SELECTOR = LabelSelector.make(match_labels={"pod": "a"})
POD_C_MATCH_LABELS_SELECTOR = LabelSelector.make(match_labels={"pod": "c"})
POD_AB_MATCH_EXPRESSIONS_SELECTOR = LabelSelector.make(
    match_expressions=[LabelSelectorRequirement("pod", OP_IN, ("a", "b"))]
)
POD_BC_MATCH_EXPRESSIONS_SELECTOR = LabelSelector.make(
    match_expressions=[LabelSelectorRequirement("pod", OP_IN, ("b", "c"))]
)
NS_X_MATCH_LABELS_SELECTOR = LabelSelector.make(match_labels={"ns": "x"})
NS_XY_MATCH_EXPRESSIONS_SELECTOR = LabelSelector.make(
    match_expressions=[LabelSelectorRequirement("ns", OP_IN, ("x", "y"))]
)
NS_YZ_MATCH_EXPRESSIONS_SELECTOR = LabelSelector.make(
    match_expressions=[LabelSelectorRequirement("ns", OP_IN, ("y", "z"))]
)


def _allow_dns_rule():
    # import here to avoid a module cycle with netpol_builder
    from .netpol_builder import Rule

    return Rule(ports=[NetworkPolicyPort(protocol=UDP, port=PORT53)], peers=[])


def allow_dns_rule():
    """A fresh AllowDNS rule (UDP:53 to all peers, constants.go:53-60)."""
    return _allow_dns_rule()


def allow_dns_policy(source):
    """constants.go:67-73."""
    from .netpol_builder import Netpol, NetpolPeers

    return Netpol(
        name="allow-dns",
        target=source,
        egress=NetpolPeers(rules=[allow_dns_rule()]),
    )


def deny_all_rules():
    return []


def allow_all_rules():
    from .netpol_builder import Rule

    return [Rule()]
