"""TestCaseGenerator (reference: generator/testcasegenerator.go)."""

from __future__ import annotations

from typing import List

from . import cases
from .testcase import TestCase


class TestCaseGenerator:
    """testcasegenerator.go:38-84: tag include/exclude filter over all 8
    case families."""

    __test__ = False  # not a pytest class

    def __init__(
        self,
        allow_dns: bool,
        pod_ip: str,
        namespaces: List[str],
        tags: List[str] = (),
        excluded_tags: List[str] = (),
    ):
        self.allow_dns = allow_dns
        self.pod_ip = pod_ip
        self.namespaces = list(namespaces)
        self.tags = list(tags)
        self.excluded_tags = list(excluded_tags)

    def target_test_cases(self) -> List[TestCase]:
        return cases.target_cases(self.namespaces)

    def rules_test_cases(self) -> List[TestCase]:
        return cases.rules_cases()

    def peers_test_cases(self) -> List[TestCase]:
        return cases.peers_cases(self.pod_ip)

    def port_protocol_test_cases(self) -> List[TestCase]:
        return cases.port_protocol_cases()

    def example_test_cases(self) -> List[TestCase]:
        return cases.example_cases()

    def action_test_cases(self) -> List[TestCase]:
        return cases.action_cases()

    def conflict_test_cases(self) -> List[TestCase]:
        return cases.conflict_cases(self.allow_dns)

    def upstream_e2e_test_cases(self) -> List[TestCase]:
        return cases.upstream_e2e_cases()

    def tier_test_cases(self):
        """The ANP/BANP precedence-tier conformance family
        (generator/anp_cases.py TierCase objects).  Differential, not
        kubectl-driven — gated kernel-vs-oracle by tests/test_tiers.py
        and `cyclonus-tpu fuzz --conformance` — so it rides alongside,
        not inside, the 216 probe-driven cases (generate_all_test_cases
        keeps its golden count)."""
        from .anp_cases import tier_cases

        return tier_cases()

    def generate_all_test_cases(self) -> List[TestCase]:
        return (
            self.target_test_cases()
            + self.rules_test_cases()
            + self.peers_test_cases()
            + self.port_protocol_test_cases()
            + self.example_test_cases()
            + self.action_test_cases()
            + self.conflict_test_cases()
            + self.upstream_e2e_test_cases()
        )

    def generate_test_cases(self) -> List[TestCase]:
        out = []
        for tc in self.generate_all_test_cases():
            if (
                not self.tags or tc.tags.contains_any(self.tags)
            ) and not tc.tags.contains_any(self.excluded_tags):
                out.append(tc)
        return out
