"""The 8 conformance case families (reference: generator/targetcases.go,
rulescases.go, peerscases.go, portprotocolcases.go, actioncases.go,
conflictcases.go, examplecases.go, upstreame2ecases.go).

Golden counts (testcasegenerator_tests.go:11-24): target 6, rules 4, peers
112, port/protocol 58, example 1, action 6, conflict 16, upstream-e2e 13."""

from __future__ import annotations

from typing import List, Optional

from ..kube.ipaddr import make_ipv4_cidr
from ..kube.labels import is_label_selector_empty, serialize_label_selector
from ..kube.netpol import (
    IPBlock,
    IntOrString,
    LabelSelector,
    LabelSelectorRequirement,
    NetworkPolicy,
    NetworkPolicyEgressRule,
    NetworkPolicyIngressRule,
    NetworkPolicyPeer,
    NetworkPolicyPort,
    NetworkPolicySpec,
    OP_IN,
    OP_NOT_IN,
)
from .actions import (
    Action,
    create_namespace,
    create_pod,
    create_policy,
    delete_namespace,
    delete_pod,
    delete_policy,
    set_namespace_labels,
    set_pod_labels,
    update_policy,
)
from .constants import (
    EMPTY_SELECTOR,
    NS_X_MATCH_LABELS_SELECTOR,
    NS_YZ_MATCH_EXPRESSIONS_SELECTOR,
    POD_AB_MATCH_EXPRESSIONS_SELECTOR,
    POD_A_MATCH_LABELS_SELECTOR,
    POD_C_MATCH_LABELS_SELECTOR,
    PORT7981,
    PORT80,
    PORT81,
    PORT_SERVE_7981_UDP,
    PORT_SERVE_80_SCTP,
    PORT_SERVE_80_TCP,
    PORT_SERVE_80_UDP,
    PORT_SERVE_81_SCTP,
    PORT_SERVE_81_TCP,
    PORT_SERVE_81_UDP,
    SCTP,
    TCP,
    UDP,
    allow_dns_policy,
    allow_dns_rule,
    probe_all_available,
    probe_port,
)
from .netpol_builder import (
    Netpol,
    NetpolPeers,
    NetpolTarget,
    Rule,
    base_test_policy,
    build_policy,
    set_namespace,
    set_peers,
    set_pod_selector,
    set_ports,
    set_rules,
)
from .tags import (
    StringSet,
    TAG_ALLOW_ALL,
    TAG_ALL_NAMESPACES,
    TAG_ALL_PODS,
    TAG_ANY_PEER,
    TAG_ANY_PORT,
    TAG_ANY_PORT_PROTOCOL,
    TAG_CONFLICT,
    TAG_CREATE_NAMESPACE,
    TAG_CREATE_POD,
    TAG_CREATE_POLICY,
    TAG_DELETE_NAMESPACE,
    TAG_DELETE_POD,
    TAG_DELETE_POLICY,
    TAG_DENY_ALL,
    TAG_EGRESS,
    TAG_EXAMPLE,
    TAG_INGRESS,
    TAG_IP_BLOCK_NO_EXCEPT,
    TAG_IP_BLOCK_WITH_EXCEPT,
    TAG_MULTI_PEER,
    TAG_MULTI_PORT_PROTOCOL,
    TAG_NAMED_PORT,
    TAG_NAMESPACES_BY_LABEL,
    TAG_NUMBERED_PORT,
    TAG_PATHOLOGICAL,
    TAG_PODS_BY_LABEL,
    TAG_POLICY_NAMESPACE,
    TAG_SCTP,
    TAG_SET_NAMESPACE_LABELS,
    TAG_SET_POD_LABELS,
    TAG_TARGET_NAMESPACE,
    TAG_TARGET_POD_SELECTOR,
    TAG_TCP,
    TAG_UDP,
    TAG_UPDATE_POLICY,
    TAG_UPSTREAM_E2E,
)
from .testcase import TestCase, TestStep, new_single_step_test_case, new_test_case


def describe_directionality(is_ingress: bool) -> str:
    return TAG_INGRESS if is_ingress else TAG_EGRESS


def describe_port(port: Optional[IntOrString]) -> str:
    if port is None:
        return TAG_ANY_PORT
    return TAG_NUMBERED_PORT if port.is_int else TAG_NAMED_PORT


def describe_protocol(protocol: Optional[str]) -> Optional[str]:
    if protocol is None:
        return None
    return {"TCP": TAG_TCP, "UDP": TAG_UDP, "SCTP": TAG_SCTP}[protocol]


# ---------------------------------------------------------------------------
# target cases (targetcases.go)
# ---------------------------------------------------------------------------


def target_cases(namespaces: List[str]) -> List[TestCase]:
    cases = []
    for ns in namespaces:
        cases.append(
            new_single_step_test_case(
                f"set namespace to {ns}",
                StringSet.of(TAG_TARGET_NAMESPACE),
                probe_all_available(),
                create_policy(build_policy(set_namespace(ns)).network_policy()),
            )
        )
    for selector in (
        EMPTY_SELECTOR,
        POD_A_MATCH_LABELS_SELECTOR,
        POD_AB_MATCH_EXPRESSIONS_SELECTOR,
    ):
        cases.append(
            new_single_step_test_case(
                f"set pod selector to {serialize_label_selector(selector)}",
                StringSet.of(TAG_TARGET_POD_SELECTOR),
                probe_all_available(),
                create_policy(build_policy(set_pod_selector(selector)).network_policy()),
            )
        )
    return cases


# ---------------------------------------------------------------------------
# rules cases (rulescases.go)
# ---------------------------------------------------------------------------


def rules_cases() -> List[TestCase]:
    cases = []
    for is_ingress in (False, True):
        direction = describe_directionality(is_ingress)
        cases.append(
            new_single_step_test_case(
                f"{direction}: deny all",
                StringSet.of(direction, TAG_DENY_ALL),
                probe_all_available(),
                create_policy(build_policy(set_rules(is_ingress, [])).network_policy()),
            )
        )
        cases.append(
            new_single_step_test_case(
                f"{direction}: allow all",
                StringSet.of(direction, TAG_ALLOW_ALL),
                probe_all_available(),
                create_policy(
                    build_policy(set_rules(is_ingress, [Rule()])).network_policy()
                ),
            )
        )
    return cases


# ---------------------------------------------------------------------------
# peers cases (peerscases.go)
# ---------------------------------------------------------------------------


class _DescribedPeer:
    def __init__(self, description: str, peer: NetworkPolicyPeer):
        self.description = description
        self.peer = peer


def _pod_peers() -> List[_DescribedPeer]:
    return [
        _DescribedPeer(
            "empty pods + nil ns", NetworkPolicyPeer(pod_selector=EMPTY_SELECTOR)
        ),
        _DescribedPeer(
            "pods by label + nil ns",
            NetworkPolicyPeer(pod_selector=POD_C_MATCH_LABELS_SELECTOR),
        ),
        _DescribedPeer(
            "nil pods + empty ns", NetworkPolicyPeer(namespace_selector=EMPTY_SELECTOR)
        ),
        _DescribedPeer(
            "empty pods + empty ns",
            NetworkPolicyPeer(
                pod_selector=EMPTY_SELECTOR, namespace_selector=EMPTY_SELECTOR
            ),
        ),
        _DescribedPeer(
            "pods by label + empty ns",
            NetworkPolicyPeer(
                pod_selector=POD_C_MATCH_LABELS_SELECTOR,
                namespace_selector=EMPTY_SELECTOR,
            ),
        ),
        _DescribedPeer(
            "nil pods + ns by label",
            NetworkPolicyPeer(namespace_selector=NS_X_MATCH_LABELS_SELECTOR),
        ),
        _DescribedPeer(
            "empty pods + ns by label",
            NetworkPolicyPeer(
                pod_selector=EMPTY_SELECTOR,
                namespace_selector=NS_X_MATCH_LABELS_SELECTOR,
            ),
        ),
        _DescribedPeer(
            "pods by label + ns by label",
            NetworkPolicyPeer(
                pod_selector=POD_C_MATCH_LABELS_SELECTOR,
                namespace_selector=NS_X_MATCH_LABELS_SELECTOR,
            ),
        ),
    ]


def _ip_block_peers(pod_ip: str) -> List[_DescribedPeer]:
    cidr24 = make_ipv4_cidr(pod_ip, 24)
    cidr28 = make_ipv4_cidr(pod_ip, 28)
    return [
        _DescribedPeer(
            "simple ipblock", NetworkPolicyPeer(ip_block=IPBlock.make(cidr24))
        ),
        _DescribedPeer(
            "ipblock with except",
            NetworkPolicyPeer(ip_block=IPBlock.make(cidr24, [cidr28])),
        ),
    ]


def _make_peers(pod_ip: str) -> List[_DescribedPeer]:
    return _pod_peers() + _ip_block_peers(pod_ip)


def _describe_peer(peer: NetworkPolicyPeer) -> List[str]:
    if peer.ip_block is not None:
        if not peer.ip_block.except_:
            return [TAG_IP_BLOCK_NO_EXCEPT]
        return [TAG_IP_BLOCK_WITH_EXCEPT]
    if peer.namespace_selector is None:
        ns_tag = TAG_POLICY_NAMESPACE
    elif is_label_selector_empty(peer.namespace_selector):
        ns_tag = TAG_ALL_NAMESPACES
    else:
        ns_tag = TAG_NAMESPACES_BY_LABEL
    if peer.pod_selector is None or is_label_selector_empty(peer.pod_selector):
        pod_tag = TAG_ALL_PODS
    else:
        pod_tag = TAG_PODS_BY_LABEL
    return [ns_tag, pod_tag]


def peers_cases(pod_ip: str) -> List[TestCase]:
    cases = []
    # zero peers
    for is_ingress in (True, False):
        direction = describe_directionality(is_ingress)
        cases.append(
            new_single_step_test_case(
                f"{direction}: empty peers",
                StringSet.of(direction, TAG_ANY_PEER),
                probe_all_available(),
                create_policy(
                    build_policy(set_peers(is_ingress, [])).network_policy()
                ),
            )
        )
    # single peers
    for is_ingress in (True, False):
        for p in _make_peers(pod_ip):
            tags = _describe_peer(p.peer) + [describe_directionality(is_ingress)]
            cases.append(
                new_single_step_test_case(
                    p.description,
                    StringSet.of(*tags),
                    probe_all_available(),
                    create_policy(
                        build_policy(set_peers(is_ingress, [p.peer])).network_policy()
                    ),
                )
            )
    # two peers
    for is_ingress in (True, False):
        described = _make_peers(pod_ip)
        for i, p1 in enumerate(described):
            for j, p2 in enumerate(described):
                if i < j:
                    direction = describe_directionality(is_ingress)
                    tags = (
                        _describe_peer(p1.peer)
                        + [TAG_MULTI_PEER, direction]
                        + _describe_peer(p2.peer)
                    )
                    cases.append(
                        new_single_step_test_case(
                            f"{direction}, 2-peer: {p1.description}, {p2.description}",
                            StringSet.of(*tags),
                            probe_all_available(),
                            create_policy(
                                build_policy(
                                    set_peers(is_ingress, [p1.peer, p2.peer])
                                ).network_policy()
                            ),
                        )
                    )
    return cases


# ---------------------------------------------------------------------------
# port/protocol cases (portprotocolcases.go)
# ---------------------------------------------------------------------------


def _network_policy_ports() -> List[NetworkPolicyPort]:
    npps = [
        NetworkPolicyPort(protocol=protocol, port=port)
        for protocol in (None, TCP, UDP, SCTP)
        for port in (None, PORT80, PORT81)
    ]
    npps.extend(
        [
            NetworkPolicyPort(protocol=TCP, port=PORT_SERVE_80_TCP),
            NetworkPolicyPort(protocol=TCP, port=PORT_SERVE_81_TCP),
            NetworkPolicyPort(protocol=UDP, port=PORT_SERVE_80_UDP),
            NetworkPolicyPort(protocol=UDP, port=PORT_SERVE_81_UDP),
            NetworkPolicyPort(protocol=SCTP, port=PORT_SERVE_80_SCTP),
            NetworkPolicyPort(protocol=SCTP, port=PORT_SERVE_81_SCTP),
        ]
    )
    return npps


def port_protocol_cases() -> List[TestCase]:
    cases = []
    # zero
    for is_ingress in (False, True):
        direction = describe_directionality(is_ingress)
        cases.append(
            new_single_step_test_case(
                f"{direction}: empty port/protocol",
                StringSet.of(direction, TAG_ANY_PORT_PROTOCOL),
                probe_all_available(),
                create_policy(
                    build_policy(set_ports(is_ingress, [])).network_policy()
                ),
            )
        )
    # single + pathological
    for is_ingress in (False, True):
        direction = describe_directionality(is_ingress)
        for npp in _network_policy_ports():
            tags = StringSet.of(direction, describe_port(npp.port))
            proto_tag = describe_protocol(npp.protocol)
            if proto_tag is not None:
                tags.add(proto_tag)
            cases.append(
                new_single_step_test_case(
                    "",
                    tags,
                    probe_all_available(),
                    create_policy(
                        build_policy(set_ports(is_ingress, [npp])).network_policy()
                    ),
                )
            )
        pathological = [
            (
                "open a named port that doesn't match its protocol",
                NetworkPolicyPort(protocol=TCP, port=PORT_SERVE_81_UDP),
            ),
            (
                "open a named port that isn't served",
                NetworkPolicyPort(protocol=TCP, port=PORT_SERVE_7981_UDP),
            ),
            (
                "open a numbered port that isn't served",
                NetworkPolicyPort(protocol=TCP, port=PORT7981),
            ),
        ]
        for description, npp in pathological:
            cases.append(
                new_single_step_test_case(
                    description,
                    StringSet.of(
                        TAG_PATHOLOGICAL, direction, describe_port(npp.port), TAG_TCP
                    ),
                    probe_all_available(),
                    create_policy(
                        build_policy(set_ports(is_ingress, [npp])).network_policy()
                    ),
                )
            )
    # two ports (portprotocolcases.go:144-168)
    npp_pairs = [
        [NetworkPolicyPort(), NetworkPolicyPort(port=PORT80)],
        [NetworkPolicyPort(), NetworkPolicyPort(port=PORT_SERVE_80_TCP)],
        [NetworkPolicyPort(), NetworkPolicyPort(protocol=UDP)],
        [NetworkPolicyPort(port=PORT80), NetworkPolicyPort(port=PORT81)],
        [NetworkPolicyPort(port=PORT80), NetworkPolicyPort(port=PORT_SERVE_81_TCP)],
        [
            NetworkPolicyPort(port=PORT80),
            NetworkPolicyPort(protocol=UDP, port=PORT_SERVE_81_UDP),
        ],
        [
            NetworkPolicyPort(protocol=UDP, port=PORT80),
            NetworkPolicyPort(protocol=UDP, port=PORT_SERVE_81_UDP),
        ],
    ]
    for is_ingress in (False, True):
        direction = describe_directionality(is_ingress)
        for npp_slice in npp_pairs:
            tags = StringSet.of(TAG_MULTI_PORT_PROTOCOL, direction)
            for pp in npp_slice:
                proto_tag = describe_protocol(pp.protocol)
                if proto_tag is not None:
                    tags.add(proto_tag)
                tags.add(describe_port(pp.port))
            cases.append(
                new_single_step_test_case(
                    "",
                    tags,
                    probe_all_available(),
                    create_policy(
                        build_policy(set_ports(is_ingress, npp_slice)).network_policy()
                    ),
                )
            )
    return cases


# ---------------------------------------------------------------------------
# action cases (actioncases.go)
# ---------------------------------------------------------------------------


def action_cases() -> List[TestCase]:
    base = base_test_policy()
    return [
        TestCase(
            description="Create/delete policy",
            tags=StringSet.of(TAG_CREATE_POLICY, TAG_DELETE_POLICY),
            steps=[
                TestStep(
                    probe_all_available(),
                    [create_policy(base_test_policy().network_policy())],
                ),
                TestStep(
                    probe_all_available(),
                    [delete_policy(base.target.namespace, base.name)],
                ),
            ],
        ),
        TestCase(
            description="Create/update policy",
            tags=StringSet.of(TAG_CREATE_POLICY, TAG_UPDATE_POLICY),
            steps=[
                TestStep(
                    probe_all_available(),
                    [create_policy(base_test_policy().network_policy())],
                ),
                TestStep(
                    probe_all_available(),
                    [
                        update_policy(
                            build_policy(
                                set_ports(
                                    True,
                                    [
                                        NetworkPolicyPort(
                                            protocol=UDP, port=PORT_SERVE_81_UDP
                                        )
                                    ],
                                )
                            ).network_policy()
                        )
                    ],
                ),
            ],
        ),
        TestCase(
            description="Create/delete namespace",
            tags=StringSet.of(TAG_CREATE_NAMESPACE, TAG_DELETE_NAMESPACE),
            steps=[
                TestStep(
                    probe_all_available(),
                    [create_policy(base_test_policy().network_policy())],
                ),
                TestStep(
                    probe_all_available(),
                    [
                        create_namespace("y-2", {"ns": "y"}),
                        create_pod("y-2", "a", {"pod": "a"}),
                        create_pod("y-2", "b", {"pod": "b"}),
                    ],
                ),
                TestStep(probe_all_available(), [delete_namespace("y-2")]),
            ],
        ),
        TestCase(
            description="Update namespace so that policy applies, then again so it no longer applies",
            tags=StringSet.of(TAG_SET_NAMESPACE_LABELS),
            steps=[
                TestStep(
                    probe_all_available(),
                    [
                        create_policy(
                            build_policy(
                                set_peers(
                                    True,
                                    [
                                        NetworkPolicyPeer(
                                            namespace_selector=LabelSelector.make(
                                                match_labels={"new-ns": "qrs"}
                                            )
                                        )
                                    ],
                                )
                            ).network_policy()
                        )
                    ],
                ),
                TestStep(
                    probe_all_available(),
                    [set_namespace_labels("y", {"ns": "y", "new-ns": "qrs"})],
                ),
                TestStep(
                    probe_all_available(),
                    [set_namespace_labels("y", {"ns": "y"})],
                ),
            ],
        ),
        TestCase(
            description="Create/delete pod",
            tags=StringSet.of(TAG_CREATE_POD, TAG_DELETE_POD),
            steps=[
                TestStep(
                    probe_all_available(),
                    [create_policy(base_test_policy().network_policy())],
                ),
                TestStep(
                    probe_all_available(), [create_pod("x", "d", {"pod": "d"})]
                ),
                TestStep(probe_all_available(), [delete_pod("x", "d")]),
            ],
        ),
        TestCase(
            description="Update pod so that policy applies, then again so it no longer applies",
            tags=StringSet.of(TAG_SET_POD_LABELS),
            steps=[
                TestStep(
                    probe_all_available(),
                    [
                        create_policy(
                            build_policy(
                                set_peers(
                                    True,
                                    [
                                        NetworkPolicyPeer(
                                            pod_selector=LabelSelector.make(
                                                match_labels={"new-label": "abc"}
                                            ),
                                            namespace_selector=NS_YZ_MATCH_EXPRESSIONS_SELECTOR,
                                        )
                                    ],
                                )
                            ).network_policy()
                        )
                    ],
                ),
                TestStep(
                    probe_all_available(),
                    [set_pod_labels("y", "b", {"pod": "b", "new-label": "abc"})],
                ),
                TestStep(
                    probe_all_available(),
                    [set_pod_labels("y", "b", {"pod": "b"})],
                ),
            ],
        ),
    ]


# ---------------------------------------------------------------------------
# conflict cases (conflictcases.go)
# ---------------------------------------------------------------------------


def _explicit_allow_all() -> NetpolPeers:
    return NetpolPeers(rules=[Rule()])


def _deny_all() -> NetpolPeers:
    return NetpolPeers(rules=[])


def _allow_all_by_pod() -> NetpolPeers:
    return NetpolPeers(
        rules=[Rule(peers=[NetworkPolicyPeer(namespace_selector=EMPTY_SELECTOR)])]
    )


def _allow_all_by_ip() -> NetpolPeers:
    return NetpolPeers(
        rules=[Rule(peers=[NetworkPolicyPeer(ip_block=IPBlock.make("0.0.0.0/0"))])]
    )


def _deny_all_by_ip() -> NetpolPeers:
    return NetpolPeers(
        rules=[Rule(peers=[NetworkPolicyPeer(ip_block=IPBlock.make("0.0.0.0/31"))])]
    )


def _deny_all_by_pod() -> NetpolPeers:
    return NetpolPeers(
        rules=[
            Rule(
                peers=[
                    NetworkPolicyPeer(
                        namespace_selector=LabelSelector.make(
                            match_labels={"this-will-never-happen": "qrs123"}
                        )
                    )
                ]
            )
        ]
    )


def conflict_cases(allow_dns: bool) -> List[TestCase]:
    """conflictcases.go:253-304.  NB the reference passes `source` for the
    last 8 slots (including the 'ingress' ones) — mirrored exactly."""
    source = NetpolTarget.make("x", {"pod": "b"})
    dest = NetpolTarget.make("y", {"pod": "c"})

    slices = [
        (
            "deny all from source, allow all to dest",
            [TAG_DENY_ALL, TAG_ALLOW_ALL, TAG_INGRESS, TAG_EGRESS],
            [
                Netpol(name="deny-all-egress", target=source, egress=_deny_all()),
                Netpol(
                    name="allow-all-ingress", target=dest, ingress=_explicit_allow_all()
                ),
            ],
        ),
        (
            "allow all from source, deny all to dest",
            [TAG_DENY_ALL, TAG_ALLOW_ALL, TAG_INGRESS, TAG_EGRESS],
            [
                Netpol(
                    name="allow-all-egress", target=source, egress=_explicit_allow_all()
                ),
                Netpol(name="deny-all-ingress", target=dest, ingress=_deny_all()),
            ],
        ),
        (
            "deny all + allow all from same source",
            [TAG_DENY_ALL, TAG_ALLOW_ALL, TAG_EGRESS],
            [
                Netpol(name="deny-all-egress", target=source, egress=_deny_all()),
                Netpol(
                    name="allow-all-egress", target=source, egress=_explicit_allow_all()
                ),
            ],
        ),
        (
            "deny all + allow all to same dest",
            [TAG_DENY_ALL, TAG_ALLOW_ALL, TAG_INGRESS],
            [
                Netpol(name="deny-all-ingress", target=dest, ingress=_deny_all()),
                Netpol(
                    name="allow-all-ingress", target=dest, ingress=_explicit_allow_all()
                ),
            ],
        ),
        (
            "deny all + allow all by pod from same source",
            [TAG_DENY_ALL, TAG_ALL_PODS, TAG_ALL_NAMESPACES, TAG_EGRESS],
            [
                Netpol(name="deny-all-egress", target=source, egress=_deny_all()),
                Netpol(
                    name="allow-all-egress-by-pod",
                    target=source,
                    egress=_allow_all_by_pod(),
                ),
            ],
        ),
        (
            "deny all + allow all by IP from same source",
            [TAG_DENY_ALL, TAG_EGRESS],
            [
                Netpol(name="deny-all-egress", target=source, egress=_deny_all()),
                Netpol(
                    name="allow-all-egress-by-ip",
                    target=source,
                    egress=_allow_all_by_ip(),
                ),
            ],
        ),
        (
            "deny all by IP + allow all by pod from same source",
            [TAG_ALL_PODS, TAG_ALL_NAMESPACES, TAG_EGRESS],
            [
                Netpol(
                    name="deny-all-egress-by-ip",
                    target=source,
                    egress=_deny_all_by_ip(),
                ),
                Netpol(
                    name="allow-all-egress-by-pod",
                    target=source,
                    egress=_allow_all_by_pod(),
                ),
            ],
        ),
        (
            "deny all by pod + allow all by IP from same source",
            [TAG_EGRESS],
            [
                Netpol(
                    name="deny-all-egress-by-pod",
                    target=source,
                    egress=_deny_all_by_pod(),
                ),
                Netpol(
                    name="allow-all-egress-by-ip",
                    target=source,
                    egress=_allow_all_by_ip(),
                ),
            ],
        ),
        (
            "deny all + allow all by pod to same source",
            [TAG_DENY_ALL, TAG_INGRESS, TAG_ALL_PODS, TAG_ALL_NAMESPACES],
            [
                Netpol(name="deny-all-ingress", target=source, ingress=_deny_all()),
                Netpol(
                    name="allow-all-ingress-by-pod",
                    target=source,
                    ingress=_allow_all_by_pod(),
                ),
            ],
        ),
        (
            "deny all + allow all by IP to same source",
            [TAG_DENY_ALL, TAG_INGRESS],
            [
                Netpol(name="deny-all-ingress", target=source, ingress=_deny_all()),
                Netpol(
                    name="allow-all-ingress-by-ip",
                    target=source,
                    ingress=_allow_all_by_ip(),
                ),
            ],
        ),
        (
            "deny all by IP + allow all by pod to same source",
            [TAG_INGRESS, TAG_ALL_PODS, TAG_ALL_NAMESPACES],
            [
                Netpol(
                    name="deny-all-ingress-by-ip",
                    target=source,
                    ingress=_deny_all_by_ip(),
                ),
                Netpol(
                    name="allow-all-ingress-by-pod",
                    target=source,
                    ingress=_allow_all_by_pod(),
                ),
            ],
        ),
        (
            "deny all by pod + allow all by IP to same source",
            [TAG_INGRESS],
            [
                Netpol(
                    name="deny-all-ingress-by-pod",
                    target=source,
                    ingress=_deny_all_by_pod(),
                ),
                Netpol(
                    name="allow-all-ingress-by-ip",
                    target=source,
                    ingress=_allow_all_by_ip(),
                ),
            ],
        ),
        (
            "egress: deny all by IP",
            [TAG_EGRESS],
            [
                Netpol(
                    name="deny-all-egress-by-ip",
                    target=source,
                    egress=_deny_all_by_ip(),
                )
            ],
        ),
        (
            "egress: deny all by pod",
            [TAG_EGRESS],
            [
                Netpol(
                    name="deny-all-egress-by-ip",
                    target=source,
                    egress=_deny_all_by_pod(),
                )
            ],
        ),
        (
            "ingress: deny all by IP",
            [TAG_INGRESS],
            [
                Netpol(
                    name="deny-all-ingress-by-ip",
                    target=source,
                    ingress=_deny_all_by_ip(),
                )
            ],
        ),
        (
            "ingress: deny all by pod",
            [TAG_INGRESS],
            [
                Netpol(
                    name="deny-all-ingress-by-ip",
                    target=source,
                    ingress=_deny_all_by_pod(),
                )
            ],
        ),
    ]

    cases = []
    for description, tag_list, policies in slices:
        actions = []
        has_egress = False
        for pol in policies:
            if pol.egress is not None:
                has_egress = True
            actions.append(create_policy(pol.network_policy()))
        if has_egress and allow_dns:
            actions.append(create_policy(allow_dns_policy(source).network_policy()))
        tags = StringSet.of(*tag_list)
        tags.add(TAG_CONFLICT)
        cases.append(
            new_single_step_test_case(
                description, tags, probe_all_available(), *actions
            )
        )
    return cases


# ---------------------------------------------------------------------------
# example cases (examplecases.go)
# ---------------------------------------------------------------------------


def example_cases() -> List[TestCase]:
    policy = NetworkPolicy(
        name="allow-all",
        namespace="x",
        spec=NetworkPolicySpec(
            pod_selector=EMPTY_SELECTOR,
            policy_types=["Ingress"],
            ingress=[
                NetworkPolicyIngressRule(
                    ports=[NetworkPolicyPort(port=PORT_SERVE_81_TCP)]
                )
            ],
        ),
    )
    return [
        new_test_case(
            "should allow ingress access on one named port",
            StringSet.of(TAG_EXAMPLE),
            TestStep(probe_all_available(), [create_policy(policy)]),
            TestStep(
                probe_all_available(),
                [
                    create_namespace("w", {"ns": "w"}),
                    create_pod("w", "a", {"pod": "a"}),
                ],
            ),
            TestStep(probe_all_available(), [delete_pod("w", "a")]),
            TestStep(probe_all_available(), [delete_namespace("w")]),
            TestStep(probe_all_available(), []),
            TestStep(probe_port(PORT81, TCP), []),
            TestStep(probe_port(PORT_SERVE_81_TCP, TCP), []),
        )
    ]


# ---------------------------------------------------------------------------
# upstream e2e cases (upstreame2ecases.go)
# ---------------------------------------------------------------------------


def _np(name, ns, pod_selector, types, ingress=None, egress=None) -> NetworkPolicy:
    return NetworkPolicy(
        name=name,
        namespace=ns,
        spec=NetworkPolicySpec(
            pod_selector=pod_selector,
            policy_types=types,
            ingress=ingress or [],
            egress=egress or [],
        ),
    )


def upstream_e2e_cases() -> List[TestCase]:
    probe = probe_all_available
    cases = [
        new_single_step_test_case(
            "should support a 'default-deny-ingress' policy",
            StringSet.of(TAG_UPSTREAM_E2E, TAG_INGRESS, TAG_DENY_ALL),
            probe(),
            create_policy(_np("deny-ingress", "x", EMPTY_SELECTOR, ["Ingress"])),
        ),
        new_single_step_test_case(
            "should support a 'default-deny-all' policy",
            StringSet.of(TAG_UPSTREAM_E2E, TAG_DENY_ALL),
            probe(),
            create_policy(
                _np(
                    "deny-all-allow-dns",
                    "x",
                    EMPTY_SELECTOR,
                    ["Egress", "Ingress"],
                    egress=[allow_dns_rule().egress()],
                )
            ),
        ),
        new_single_step_test_case(
            "should enforce policy based on Multiple PodSelectors and NamespaceSelectors",
            StringSet.of(TAG_UPSTREAM_E2E),
            probe(),
            create_policy(
                _np(
                    "allow-ns-y-z-pod-b-c",
                    "x",
                    POD_A_MATCH_LABELS_SELECTOR,
                    ["Ingress"],
                    ingress=[
                        NetworkPolicyIngressRule(
                            from_=[
                                NetworkPolicyPeer(
                                    namespace_selector=LabelSelector.make(
                                        match_expressions=[
                                            LabelSelectorRequirement(
                                                "ns", OP_NOT_IN, ("x",)
                                            )
                                        ]
                                    ),
                                    pod_selector=LabelSelector.make(
                                        match_expressions=[
                                            LabelSelectorRequirement(
                                                "pod", OP_IN, ("b", "c")
                                            )
                                        ]
                                    ),
                                )
                            ]
                        )
                    ],
                )
            ),
        ),
        new_test_case(
            "should enforce multiple, stacked policies with overlapping podSelectors [Feature:NetworkPolicy]",
            StringSet.of(TAG_UPSTREAM_E2E),
            TestStep(
                probe(),
                [
                    create_policy(
                        _np(
                            "allow-client-a-via-ns-selector-81",
                            "x",
                            POD_A_MATCH_LABELS_SELECTOR,
                            ["Ingress"],
                            ingress=[
                                NetworkPolicyIngressRule(
                                    from_=[
                                        NetworkPolicyPeer(
                                            namespace_selector=LabelSelector.make(
                                                match_labels={"ns": "y"}
                                            )
                                        )
                                    ],
                                    ports=[NetworkPolicyPort(protocol=TCP, port=PORT81)],
                                )
                            ],
                        )
                    )
                ],
            ),
            TestStep(probe(), []),
            TestStep(
                probe(),
                [
                    create_policy(
                        _np(
                            "allow-client-a-via-ns-selector-80",
                            "x",
                            POD_A_MATCH_LABELS_SELECTOR,
                            ["Ingress"],
                            ingress=[
                                NetworkPolicyIngressRule(
                                    from_=[
                                        NetworkPolicyPeer(
                                            namespace_selector=LabelSelector.make(
                                                match_labels={"ns": "y"}
                                            )
                                        )
                                    ],
                                    ports=[NetworkPolicyPort(protocol=TCP, port=PORT80)],
                                )
                            ],
                        )
                    )
                ],
            ),
        ),
        new_test_case(
            "should support allow-all policy",
            StringSet.of(TAG_UPSTREAM_E2E, TAG_ALLOW_ALL),
            TestStep(
                probe(),
                [
                    create_policy(
                        _np(
                            "allow-all",
                            "x",
                            EMPTY_SELECTOR,
                            ["Ingress"],
                            ingress=[NetworkPolicyIngressRule()],
                        )
                    )
                ],
            ),
            TestStep(probe(), []),
        ),
        new_test_case(
            "should allow ingress access on one named port",
            StringSet.of(TAG_UPSTREAM_E2E, TAG_INGRESS, TAG_NAMED_PORT),
            TestStep(
                probe_port(PORT_SERVE_81_TCP, TCP),
                [
                    create_policy(
                        _np(
                            "allow-all",
                            "x",
                            EMPTY_SELECTOR,
                            ["Ingress"],
                            ingress=[
                                NetworkPolicyIngressRule(
                                    ports=[
                                        NetworkPolicyPort(port=PORT_SERVE_81_TCP)
                                    ]
                                )
                            ],
                        )
                    )
                ],
            ),
            TestStep(probe(), []),
        ),
        new_test_case(
            "should enforce updated policy",
            StringSet.of(TAG_UPSTREAM_E2E),
            TestStep(
                probe(),
                [
                    create_policy(
                        _np(
                            "allow-all-mutate-to-deny-all",
                            "x",
                            EMPTY_SELECTOR,
                            ["Ingress"],
                            ingress=[NetworkPolicyIngressRule()],
                        )
                    )
                ],
            ),
            TestStep(
                probe(),
                [
                    update_policy(
                        _np(
                            "allow-all-mutate-to-deny-all",
                            "x",
                            EMPTY_SELECTOR,
                            ["Ingress"],
                        )
                    )
                ],
            ),
        ),
        new_test_case(
            "should allow ingress access from updated namespace",
            StringSet.of(TAG_UPSTREAM_E2E),
            TestStep(
                probe(),
                [
                    create_policy(
                        _np(
                            "allow-client-a-via-ns-selector",
                            "x",
                            POD_A_MATCH_LABELS_SELECTOR,
                            ["Ingress"],
                            ingress=[
                                NetworkPolicyIngressRule(
                                    from_=[
                                        NetworkPolicyPeer(
                                            namespace_selector=LabelSelector.make(
                                                match_labels={"ns2": "updated"}
                                            )
                                        )
                                    ]
                                )
                            ],
                        )
                    )
                ],
            ),
            TestStep(
                probe(),
                [set_namespace_labels("y", {"ns": "y", "ns2": "updated"})],
            ),
        ),
        new_test_case(
            "should allow ingress access from updated pod",
            StringSet.of(TAG_UPSTREAM_E2E),
            TestStep(
                probe(),
                [
                    create_policy(
                        _np(
                            "allow-client-a-via-pod-selector",
                            "x",
                            POD_A_MATCH_LABELS_SELECTOR,
                            ["Ingress"],
                            ingress=[
                                NetworkPolicyIngressRule(
                                    from_=[
                                        NetworkPolicyPeer(
                                            pod_selector=LabelSelector.make(
                                                match_labels={
                                                    "pod": "b",
                                                    "pod2": "updated",
                                                }
                                            )
                                        )
                                    ]
                                )
                            ],
                        )
                    )
                ],
            ),
            TestStep(
                probe(),
                [set_pod_labels("x", "b", {"pod": "b", "pod2": "updated"})],
            ),
        ),
        new_test_case(
            "should deny ingress access to updated pod",
            StringSet.of(TAG_UPSTREAM_E2E),
            TestStep(
                probe(),
                [
                    create_policy(
                        _np(
                            "deny-ingress-via-label-selector",
                            "x",
                            LabelSelector.make(match_labels={"target": "isolated"}),
                            ["Ingress"],
                        )
                    )
                ],
            ),
            TestStep(probe(), [set_pod_labels("x", "a", {"target": "isolated"})]),
        ),
        new_test_case(
            "should work with Ingress, Egress specified together",
            StringSet.of(TAG_UPSTREAM_E2E),
            TestStep(
                probe(),
                [
                    create_policy(
                        _np(
                            "allow-client-a-via-pod-selector",
                            "x",
                            POD_A_MATCH_LABELS_SELECTOR,
                            ["Ingress", "Egress"],
                            ingress=[
                                NetworkPolicyIngressRule(
                                    from_=[
                                        NetworkPolicyPeer(
                                            pod_selector=LabelSelector.make(
                                                match_labels={"pod": "b"}
                                            )
                                        )
                                    ]
                                )
                            ],
                            egress=[
                                NetworkPolicyEgressRule(
                                    ports=[
                                        NetworkPolicyPort(port=PORT80),
                                        NetworkPolicyPort(
                                            protocol=UDP, port=IntOrString(53)
                                        ),
                                    ]
                                )
                            ],
                        )
                    )
                ],
            ),
            TestStep(probe(), []),
        ),
        new_test_case(
            "should support denying of egress traffic on the client side (even if the server explicitly allows this traffic)",
            StringSet.of(TAG_UPSTREAM_E2E, TAG_CONFLICT),
            TestStep(
                probe(),
                [
                    create_policy(
                        _np(
                            "allow-to-ns-y-pod-a",
                            "x",
                            POD_A_MATCH_LABELS_SELECTOR,
                            ["Egress"],
                            egress=[
                                NetworkPolicyEgressRule(
                                    to=[
                                        NetworkPolicyPeer(
                                            namespace_selector=LabelSelector.make(
                                                match_labels={"ns": "y"}
                                            ),
                                            pod_selector=POD_A_MATCH_LABELS_SELECTOR,
                                        )
                                    ]
                                ),
                                NetworkPolicyEgressRule(
                                    ports=[
                                        NetworkPolicyPort(
                                            protocol=UDP, port=IntOrString(53)
                                        )
                                    ]
                                ),
                            ],
                        )
                    ),
                    create_policy(
                        _np(
                            "allow-from-xa-on-ya-match-selector",
                            "y",
                            POD_A_MATCH_LABELS_SELECTOR,
                            ["Ingress"],
                            ingress=[
                                NetworkPolicyIngressRule(
                                    from_=[
                                        NetworkPolicyPeer(
                                            namespace_selector=LabelSelector.make(
                                                match_labels={"ns": "x"}
                                            ),
                                            pod_selector=POD_A_MATCH_LABELS_SELECTOR,
                                        )
                                    ]
                                )
                            ],
                        )
                    ),
                    create_policy(
                        _np(
                            "allow-from-xa-on-yb-match-selector",
                            "y",
                            LabelSelector.make(match_labels={"pod": "b"}),
                            ["Ingress"],
                            ingress=[
                                NetworkPolicyIngressRule(
                                    from_=[
                                        NetworkPolicyPeer(
                                            namespace_selector=LabelSelector.make(
                                                match_labels={"ns": "x"}
                                            ),
                                            pod_selector=POD_A_MATCH_LABELS_SELECTOR,
                                        )
                                    ]
                                )
                            ],
                        )
                    ),
                ],
            ),
        ),
        new_test_case(
            "should stop enforcing policies after they are deleted",
            StringSet.of(TAG_UPSTREAM_E2E, TAG_DENY_ALL, TAG_DELETE_POLICY),
            TestStep(
                probe(),
                [
                    create_policy(
                        _np("deny-all", "x", EMPTY_SELECTOR, ["Ingress", "Egress"])
                    )
                ],
            ),
            TestStep(probe(), [delete_policy("x", "deny-all")]),
        ),
    ]
    return cases
