"""Two-level tag taxonomy (reference: generator/tags.go): 10 primary tags,
34 subordinate tags; adding a subordinate auto-adds its primary."""

from __future__ import annotations

from typing import Dict, List

TAG_ACTION = "action"
TAG_TARGET = "target"
TAG_DIRECTION = "direction"
TAG_POLICY_STACK = "policy-stack"
TAG_RULE = "rule"
TAG_PROTOCOL = "protocol"
TAG_PORT = "port"
TAG_PEER_IPBLOCK = "peer-ipblock"
TAG_PEER_PODS = "peer-pods"
TAG_MISCELLANEOUS = "miscellaneous"

TAG_CREATE_POLICY = "create-policy"
TAG_DELETE_POLICY = "delete-policy"
TAG_UPDATE_POLICY = "update-policy"
TAG_CREATE_POD = "create-pod"
TAG_DELETE_POD = "delete-pod"
TAG_SET_POD_LABELS = "set-pod-labels"
TAG_CREATE_NAMESPACE = "create-namespace"
TAG_DELETE_NAMESPACE = "delete-namespace"
TAG_SET_NAMESPACE_LABELS = "set-namespace-labels"

TAG_TARGET_NAMESPACE = "target-namespace"
TAG_TARGET_POD_SELECTOR = "target-pod-selector"

TAG_INGRESS = "ingress"
TAG_EGRESS = "egress"

TAG_DENY_ALL = "deny-all"
TAG_ALLOW_ALL = "allow-all"
TAG_ANY_PEER = "any-peer"
TAG_ANY_PORT_PROTOCOL = "any-port-protocol"
TAG_MULTI_PEER = "multi-peer"
TAG_MULTI_PORT_PROTOCOL = "multi-port/protocol"

TAG_ALL_PODS = "all-pods"
TAG_PODS_BY_LABEL = "pods-by-label"
TAG_ALL_NAMESPACES = "all-namespaces"
TAG_NAMESPACES_BY_LABEL = "namespaces-by-label"
TAG_POLICY_NAMESPACE = "policy-namespace"

TAG_IP_BLOCK_NO_EXCEPT = "ip-block-no-except"
TAG_IP_BLOCK_WITH_EXCEPT = "ip-block-with-except"

TAG_ANY_PORT = "any-port"
TAG_NUMBERED_PORT = "numbered-port"
TAG_NAMED_PORT = "named-port"

TAG_TCP = "tcp"
TAG_UDP = "udp"
TAG_SCTP = "sctp"

TAG_PATHOLOGICAL = "pathological"
TAG_CONFLICT = "conflict"
TAG_EXAMPLE = "example"
TAG_UPSTREAM_E2E = "upstream-e2e"

# precedence-tier subordinates (the ANP/BANP conformance family,
# generator/anp_cases.py) — filed under the previously-empty
# policy-stack primary: tier cases are exactly about how stacked
# policy layers compose
TAG_ANP = "admin-network-policy"
TAG_BANP = "baseline-admin-network-policy"
TAG_TIER_PASS = "tier-pass"
TAG_DEFAULT_DENY_NS = "per-namespace-default-deny"

ALL_TAGS: Dict[str, List[str]] = {
    TAG_ACTION: [
        TAG_CREATE_POLICY,
        TAG_DELETE_POLICY,
        TAG_UPDATE_POLICY,
        TAG_CREATE_POD,
        TAG_DELETE_POD,
        TAG_SET_POD_LABELS,
        TAG_CREATE_NAMESPACE,
        TAG_DELETE_NAMESPACE,
        TAG_SET_NAMESPACE_LABELS,
    ],
    TAG_TARGET: [TAG_TARGET_NAMESPACE, TAG_TARGET_POD_SELECTOR],
    TAG_DIRECTION: [TAG_INGRESS, TAG_EGRESS],
    TAG_POLICY_STACK: [
        TAG_ANP,
        TAG_BANP,
        TAG_TIER_PASS,
        TAG_DEFAULT_DENY_NS,
    ],
    TAG_RULE: [
        TAG_DENY_ALL,
        TAG_ALLOW_ALL,
        TAG_ANY_PEER,
        TAG_ANY_PORT_PROTOCOL,
        TAG_MULTI_PEER,
        TAG_MULTI_PORT_PROTOCOL,
    ],
    TAG_PEER_PODS: [
        TAG_ALL_PODS,
        TAG_PODS_BY_LABEL,
        TAG_ALL_NAMESPACES,
        TAG_NAMESPACES_BY_LABEL,
        TAG_POLICY_NAMESPACE,
    ],
    TAG_PEER_IPBLOCK: [TAG_IP_BLOCK_NO_EXCEPT, TAG_IP_BLOCK_WITH_EXCEPT],
    TAG_PORT: [TAG_ANY_PORT, TAG_NUMBERED_PORT, TAG_NAMED_PORT],
    TAG_PROTOCOL: [TAG_TCP, TAG_UDP, TAG_SCTP],
    TAG_MISCELLANEOUS: [
        TAG_PATHOLOGICAL,
        TAG_CONFLICT,
        TAG_EXAMPLE,
        TAG_UPSTREAM_E2E,
    ],
}

TAG_SET: Dict[str, bool] = {}
TAG_SLICE: List[str] = []
TAG_SUB_TO_PRIMARY: Dict[str, str] = {}

for _primary, _subs in ALL_TAGS.items():
    TAG_SET[_primary] = True
    TAG_SLICE.append(_primary)
    for _sub in _subs:
        TAG_SET[_sub] = True
        TAG_SLICE.append(_sub)
        if _sub in TAG_SUB_TO_PRIMARY:
            raise ValueError(f"subordinate tag {_sub} has multiple owners")
        TAG_SUB_TO_PRIMARY[_sub] = _primary
TAG_SLICE.sort()


def must_get_primary_tag(sub: str) -> str:
    if sub not in TAG_SUB_TO_PRIMARY:
        raise KeyError(f"no primary tag found for {sub}")
    return TAG_SUB_TO_PRIMARY[sub]


class StringSet(dict):
    """tags.go:197-248: a set that auto-adds each subordinate's primary."""

    @staticmethod
    def of(*elems: str) -> "StringSet":
        s = StringSet()
        for e in elems:
            s.add(e)
        return s

    def add(self, key: str) -> None:
        self[key] = True
        if key in TAG_SUB_TO_PRIMARY:
            self[TAG_SUB_TO_PRIMARY[key]] = True
        elif key not in ALL_TAGS:
            raise KeyError(f"tag {key} is neither primary nor subordinate")

    def keys_sorted(self) -> List[str]:
        return sorted(self.keys())

    def contains_any(self, elems: List[str]) -> bool:
        return any(e in self for e in elems)

    def group_tags(self) -> Dict[str, List[str]]:
        grouped: Dict[str, List[str]] = {}
        for tag in self:
            if tag in ALL_TAGS:
                grouped.setdefault(tag, [])
            else:
                primary = must_get_primary_tag(tag)
                grouped.setdefault(primary, []).append(tag)
        return grouped


def count_test_cases_by_tag(test_cases) -> Dict[str, int]:
    counts = {tag: 0 for tag in TAG_SET}
    for tc in test_cases:
        for key in tc.tags:
            counts[key] += 1
    return counts


def validate_tags(tags: List[str]) -> None:
    invalid = [t for t in tags if t not in TAG_SET]
    if invalid:
        raise ValueError(f"invalid tags: {', '.join(invalid)}")
