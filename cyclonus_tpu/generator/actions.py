"""Cluster perturbation actions — a 10-variant sum type
(reference: generator/action.go)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..kube.netpol import NetworkPolicy


@dataclass
class CreatePolicyAction:
    policy: NetworkPolicy


@dataclass
class UpdatePolicyAction:
    policy: NetworkPolicy


@dataclass
class DeletePolicyAction:
    namespace: str
    name: str


@dataclass
class CreateNamespaceAction:
    namespace: str
    labels: Dict[str, str]


@dataclass
class SetNamespaceLabelsAction:
    namespace: str
    labels: Dict[str, str]


@dataclass
class DeleteNamespaceAction:
    namespace: str


@dataclass
class ReadNetworkPoliciesAction:
    namespaces: List[str]


@dataclass
class CreatePodAction:
    namespace: str
    pod: str
    labels: Dict[str, str]


@dataclass
class SetPodLabelsAction:
    namespace: str
    pod: str
    labels: Dict[str, str]


@dataclass
class DeletePodAction:
    namespace: str
    pod: str


@dataclass
class Action:
    """Exactly one field is non-None (action.go:5-20)."""

    create_policy: Optional[CreatePolicyAction] = None
    update_policy: Optional[UpdatePolicyAction] = None
    delete_policy: Optional[DeletePolicyAction] = None
    create_namespace: Optional[CreateNamespaceAction] = None
    set_namespace_labels: Optional[SetNamespaceLabelsAction] = None
    delete_namespace: Optional[DeleteNamespaceAction] = None
    read_network_policies: Optional[ReadNetworkPoliciesAction] = None
    create_pod: Optional[CreatePodAction] = None
    set_pod_labels: Optional[SetPodLabelsAction] = None
    delete_pod: Optional[DeletePodAction] = None


def create_policy(policy: NetworkPolicy) -> Action:
    return Action(create_policy=CreatePolicyAction(policy=policy))


def update_policy(policy: NetworkPolicy) -> Action:
    return Action(update_policy=UpdatePolicyAction(policy=policy))


def delete_policy(ns: str, name: str) -> Action:
    return Action(delete_policy=DeletePolicyAction(namespace=ns, name=name))


def create_namespace(ns: str, labels: Dict[str, str]) -> Action:
    return Action(create_namespace=CreateNamespaceAction(namespace=ns, labels=labels))


def set_namespace_labels(ns: str, labels: Dict[str, str]) -> Action:
    return Action(
        set_namespace_labels=SetNamespaceLabelsAction(namespace=ns, labels=labels)
    )


def delete_namespace(ns: str) -> Action:
    return Action(delete_namespace=DeleteNamespaceAction(namespace=ns))


def read_network_policies(namespaces: List[str]) -> Action:
    return Action(
        read_network_policies=ReadNetworkPoliciesAction(namespaces=namespaces)
    )


def create_pod(ns: str, pod: str, labels: Dict[str, str]) -> Action:
    return Action(create_pod=CreatePodAction(namespace=ns, pod=pod, labels=labels))


def set_pod_labels(ns: str, pod: str, labels: Dict[str, str]) -> Action:
    return Action(
        set_pod_labels=SetPodLabelsAction(namespace=ns, pod=pod, labels=labels)
    )


def delete_pod(ns: str, pod: str) -> Action:
    return Action(delete_pod=DeletePodAction(namespace=ns, pod=pod))
