"""The Netpol builder DSL (reference: generator/netpol.go): a symmetric
Target/Ingress/Egress view of NetworkPolicy plus functional setters over a
base test policy."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..kube.netpol import (
    LabelSelector,
    LabelSelectorRequirement,
    NetworkPolicy,
    NetworkPolicyEgressRule,
    NetworkPolicyIngressRule,
    NetworkPolicyPeer,
    NetworkPolicyPort,
    NetworkPolicySpec,
    OP_IN,
    POLICY_TYPE_EGRESS,
    POLICY_TYPE_INGRESS,
)
from .constants import (
    allow_dns_rule,
    NS_XY_MATCH_EXPRESSIONS_SELECTOR,
    NS_YZ_MATCH_EXPRESSIONS_SELECTOR,
    POD_AB_MATCH_EXPRESSIONS_SELECTOR,
    POD_BC_MATCH_EXPRESSIONS_SELECTOR,
    PORT80,
    TCP,
)


@dataclass
class Rule:
    """netpol.go:105-108: ports x peers, direction-agnostic."""

    ports: List[NetworkPolicyPort] = field(default_factory=list)
    peers: List[NetworkPolicyPeer] = field(default_factory=list)

    def ingress(self) -> NetworkPolicyIngressRule:
        return NetworkPolicyIngressRule(ports=list(self.ports), from_=list(self.peers))

    def egress(self) -> NetworkPolicyEgressRule:
        return NetworkPolicyEgressRule(ports=list(self.ports), to=list(self.peers))


@dataclass
class NetpolTarget:
    namespace: str
    pod_selector: LabelSelector

    @staticmethod
    def make(
        namespace: str,
        match_labels: Optional[Dict[str, str]] = None,
        match_expressions: Optional[List[LabelSelectorRequirement]] = None,
    ) -> "NetpolTarget":
        return NetpolTarget(
            namespace=namespace,
            pod_selector=LabelSelector.make(match_labels, match_expressions),
        )


@dataclass
class NetpolPeers:
    rules: List[Rule] = field(default_factory=list)


@dataclass
class Netpol:
    """netpol.go:11-17.  ingress/egress None means that PolicyType is
    absent; an empty rules list means deny-all in that direction."""

    name: str
    target: NetpolTarget
    ingress: Optional[NetpolPeers] = None
    egress: Optional[NetpolPeers] = None
    description: str = ""

    @staticmethod
    def from_network_policy(policy: NetworkPolicy) -> "Netpol":
        """netpol.go:19-43 (both directions always present in this view)."""
        return Netpol(
            name=policy.namespace,
            description="generated from NetworkPolicy",
            target=NetpolTarget(
                namespace=policy.namespace, pod_selector=policy.spec.pod_selector
            ),
            ingress=NetpolPeers(
                rules=[Rule(ports=r.ports, peers=r.from_) for r in policy.spec.ingress]
            ),
            egress=NetpolPeers(
                rules=[Rule(ports=r.ports, peers=r.to) for r in policy.spec.egress]
            ),
        )

    def network_policy(self) -> NetworkPolicy:
        """netpol.go:45-84; raises on 0 policy types."""
        types: List[str] = []
        ingress: List[NetworkPolicyIngressRule] = []
        egress: List[NetworkPolicyEgressRule] = []
        if self.ingress is not None:
            types.append(POLICY_TYPE_INGRESS)
            ingress = [r.ingress() for r in self.ingress.rules]
        if self.egress is not None:
            types.append(POLICY_TYPE_EGRESS)
            egress = [r.egress() for r in self.egress.rules]
        if not types:
            raise ValueError("cannot have 0 policy types")
        return NetworkPolicy(
            name=self.name,
            namespace=self.target.namespace,
            spec=NetworkPolicySpec(
                pod_selector=self.target.pod_selector,
                policy_types=types,
                ingress=ingress,
                egress=egress,
            ),
        )


Setter = Callable[[Netpol], None]


def set_description(description: str) -> Setter:
    def s(policy: Netpol) -> None:
        policy.description = description

    return s


def set_namespace(ns: str) -> Setter:
    def s(policy: Netpol) -> None:
        policy.target.namespace = ns

    return s


def set_pod_selector(selector: LabelSelector) -> Setter:
    def s(policy: Netpol) -> None:
        policy.target.pod_selector = selector

    return s


def set_rules(is_ingress: bool, rules: List[Rule]) -> Setter:
    def s(policy: Netpol) -> None:
        if is_ingress:
            policy.ingress.rules = rules
        else:
            policy.egress.rules = rules

    return s


def set_ports(is_ingress: bool, ports: List[NetworkPolicyPort]) -> Setter:
    def s(policy: Netpol) -> None:
        if is_ingress:
            policy.ingress.rules[0].ports = ports
        else:
            policy.egress.rules[0].ports = ports

    return s


def set_peers(is_ingress: bool, peers: List[NetworkPolicyPeer]) -> Setter:
    def s(policy: Netpol) -> None:
        if is_ingress:
            policy.ingress.rules[0].peers = peers
        else:
            policy.egress.rules[0].peers = peers

    return s


def base_test_policy() -> Netpol:
    """netpol.go:195-226: target x/pod:a; ingress TCP:80 from pods b,c in
    ns x,y; egress TCP:80 to pods a,b in ns y,z + AllowDNS."""
    return Netpol(
        name="base",
        target=NetpolTarget(
            namespace="x",
            pod_selector=LabelSelector.make(match_labels={"pod": "a"}),
        ),
        ingress=NetpolPeers(
            rules=[
                Rule(
                    ports=[NetworkPolicyPort(protocol=TCP, port=PORT80)],
                    peers=[
                        NetworkPolicyPeer(
                            pod_selector=POD_BC_MATCH_EXPRESSIONS_SELECTOR,
                            namespace_selector=NS_XY_MATCH_EXPRESSIONS_SELECTOR,
                        )
                    ],
                )
            ]
        ),
        egress=NetpolPeers(
            rules=[
                Rule(
                    ports=[NetworkPolicyPort(protocol=TCP, port=PORT80)],
                    peers=[
                        NetworkPolicyPeer(
                            pod_selector=POD_AB_MATCH_EXPRESSIONS_SELECTOR,
                            namespace_selector=NS_YZ_MATCH_EXPRESSIONS_SELECTOR,
                        )
                    ],
                ),
                allow_dns_rule(),
            ]
        ),
    )


def build_policy(*setters: Setter) -> Netpol:
    """netpol.go:187-193."""
    policy = base_test_policy()
    for setter in setters:
        setter(policy)
    return policy
