"""Conformance test-case generation (reference: pkg/generator): the
TestCase/TestStep/Action DSL, the Netpol builder, the two-level tag
taxonomy, the feature traverser, and the 8 case families (golden counts:
target 6, rules 4, peers 112, port/protocol 58, example 1, action 6,
conflict 16, upstream-e2e 13 = 216)."""

from .actions import (
    Action,
    create_policy,
    update_policy,
    delete_policy,
    create_namespace,
    set_namespace_labels,
    delete_namespace,
    read_network_policies,
    create_pod,
    set_pod_labels,
    delete_pod,
)
from .testcase import TestCase, TestStep, new_single_step_test_case
from .netpol_builder import (
    Netpol,
    NetpolTarget,
    NetpolPeers,
    Rule,
    build_policy,
    base_test_policy,
    set_namespace,
    set_pod_selector,
    set_rules,
    set_ports,
    set_peers,
)
from .tags import (
    ALL_TAGS,
    TAG_SET,
    StringSet,
    count_test_cases_by_tag,
    validate_tags,
)
from .generator import TestCaseGenerator

__all__ = [
    "Action",
    "create_policy",
    "update_policy",
    "delete_policy",
    "create_namespace",
    "set_namespace_labels",
    "delete_namespace",
    "read_network_policies",
    "create_pod",
    "set_pod_labels",
    "delete_pod",
    "TestCase",
    "TestStep",
    "new_single_step_test_case",
    "Netpol",
    "NetpolTarget",
    "NetpolPeers",
    "Rule",
    "build_policy",
    "base_test_policy",
    "set_namespace",
    "set_pod_selector",
    "set_rules",
    "set_ports",
    "set_peers",
    "ALL_TAGS",
    "TAG_SET",
    "StringSet",
    "count_test_cases_by_tag",
    "validate_tags",
    "TestCaseGenerator",
]
