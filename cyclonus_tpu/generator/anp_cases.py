"""The precedence-tier conformance family: AdminNetworkPolicy / BANP
cases alongside the existing ~216 networkingv1 cases.

These cases are DIFFERENTIAL, not kubectl-driven: no upstream cluster
this repo drives can apply AdminNetworkPolicies (the loopback cluster
speaks networkingv1 only), so a TierCase carries the full scenario —
cluster, NetworkPolicies, TierSet, port cases — and its gate is the
fuzzer's: the tiered kernel truth table must be bit-identical to the
scalar lattice oracle (matcher/tiered.py), dense and class-compressed
alike.  tests/test_tiers.py runs every case through that gate, and
`cyclonus-tpu fuzz --conformance` runs them from the CLI.

The family doubles as executable documentation of the lattice's corner
semantics: Pass-fallthrough, deny-overrides-by-priority, equal-priority
total order, BANP-behind-NetworkPolicy shadowing, per-namespace
default-deny interplay, endPort ranges, and SCTP."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..engine.api import PortCase
from ..kube.netpol import (
    IntOrString,
    LabelSelector,
    NetworkPolicy,
    NetworkPolicyEgressRule,
    NetworkPolicyIngressRule,
    NetworkPolicyPeer,
    NetworkPolicyPort,
    NetworkPolicySpec,
)
from ..tiers.model import (
    AdminNetworkPolicy,
    BaselineAdminNetworkPolicy,
    TierPort,
    TierRule,
    TierScope,
    TierSet,
)
from .tags import (
    StringSet,
    TAG_ANP,
    TAG_BANP,
    TAG_DEFAULT_DENY_NS,
    TAG_SCTP,
    TAG_TIER_PASS,
)

PodTuple = Tuple[str, str, Dict[str, str], str]


@dataclass
class TierCase:
    """One differential conformance scenario for the verdict lattice."""

    __test__ = False  # not a pytest class

    description: str
    tags: StringSet
    tiers: TierSet
    netpols: List[NetworkPolicy] = field(default_factory=list)
    cases: List[PortCase] = field(default_factory=list)
    pods: Optional[List[PodTuple]] = None  # None: the default cluster
    namespaces: Optional[Dict[str, Dict[str, str]]] = None

    def cluster(self) -> Tuple[List[PodTuple], Dict[str, Dict[str, str]]]:
        if self.pods is not None:
            return self.pods, dict(self.namespaces or {})
        return default_tier_cluster()


def default_tier_cluster() -> Tuple[List[PodTuple], Dict[str, Dict[str, str]]]:
    """The x/y/z three-namespace, a/b/c pod grid every networkingv1
    conformance case probes, reused so tier verdicts are directly
    comparable with the base family's."""
    namespaces = {ns: {"ns": ns} for ns in ("x", "y", "z")}
    pods: List[PodTuple] = []
    ip = 1
    for ns in ("x", "y", "z"):
        for name in ("a", "b", "c"):
            pods.append((ns, name, {"pod": name}, f"192.168.2.{ip}"))
            ip += 1
    return pods, namespaces


DEFAULT_TIER_CASES = [
    PortCase(80, "serve-80-tcp", "TCP"),
    PortCase(81, "serve-81-udp", "UDP"),
    PortCase(82, "serve-82-sctp", "SCTP"),
]


def _ns_sel(ns: str) -> LabelSelector:
    return LabelSelector.make({"ns": ns})


def _pod_sel(pod: str) -> LabelSelector:
    return LabelSelector.make({"pod": pod})


def default_deny_netpol(ns: str) -> NetworkPolicy:
    """The per-namespace default-deny policy (empty podSelector, both
    directions, no rules): the generator feature the BANP-interplay and
    default-deny cases build on — and a reusable building block for any
    case family wanting an isolated-namespace baseline."""
    return NetworkPolicy(
        name=f"default-deny-{ns}",
        namespace=ns,
        spec=NetworkPolicySpec(
            pod_selector=LabelSelector.make(),
            policy_types=["Ingress", "Egress"],
        ),
    )


def per_namespace_default_deny(namespaces: List[str]) -> List[NetworkPolicy]:
    """One default-deny policy per namespace."""
    return [default_deny_netpol(ns) for ns in namespaces]


def tier_cases() -> List[TierCase]:
    """The ANP/BANP conformance family (see module docstring)."""
    out: List[TierCase] = []

    # 1. ANP Allow overrides a NetworkPolicy deny
    out.append(
        TierCase(
            description="ANP Allow at priority 10 admits traffic a "
            "namespace default-deny NetworkPolicy would drop",
            tags=StringSet.of(TAG_ANP),
            netpols=[default_deny_netpol("x")],
            tiers=TierSet(
                anps=[
                    AdminNetworkPolicy(
                        name="allow-y-into-x",
                        priority=10,
                        subject=TierScope(namespace_selector=_ns_sel("x")),
                        ingress=[
                            TierRule(
                                action="Allow",
                                peers=[TierScope(namespace_selector=_ns_sel("y"))],
                            )
                        ],
                    )
                ]
            ),
            cases=list(DEFAULT_TIER_CASES),
        )
    )

    # 2. ANP Deny overrides a NetworkPolicy allow
    out.append(
        TierCase(
            description="ANP Deny at priority 0 drops traffic a "
            "NetworkPolicy explicitly allows",
            tags=StringSet.of(TAG_ANP),
            netpols=[
                NetworkPolicy(
                    name="allow-z-into-x",
                    namespace="x",
                    spec=NetworkPolicySpec(
                        pod_selector=LabelSelector.make(),
                        policy_types=["Ingress"],
                        ingress=[
                            NetworkPolicyIngressRule(
                                from_=[
                                    NetworkPolicyPeer(
                                        namespace_selector=_ns_sel("z")
                                    )
                                ]
                            )
                        ],
                    ),
                )
            ],
            tiers=TierSet(
                anps=[
                    AdminNetworkPolicy(
                        name="deny-z",
                        priority=0,
                        subject=TierScope(),
                        ingress=[
                            TierRule(
                                action="Deny",
                                peers=[TierScope(namespace_selector=_ns_sel("z"))],
                            )
                        ],
                    )
                ]
            ),
            cases=list(DEFAULT_TIER_CASES),
        )
    )

    # 3. Pass falls through to the NetworkPolicy tier, then BANP
    out.append(
        TierCase(
            description="Pass-chain: high-priority Pass defers to a "
            "NetworkPolicy for selected pods and to BANP default-deny "
            "for the rest",
            tags=StringSet.of(TAG_ANP, TAG_BANP, TAG_TIER_PASS),
            netpols=[
                NetworkPolicy(
                    name="allow-y-into-xa",
                    namespace="x",
                    spec=NetworkPolicySpec(
                        pod_selector=_pod_sel("a"),
                        policy_types=["Ingress"],
                        ingress=[
                            NetworkPolicyIngressRule(
                                from_=[
                                    NetworkPolicyPeer(
                                        namespace_selector=_ns_sel("y")
                                    )
                                ]
                            )
                        ],
                    ),
                )
            ],
            tiers=TierSet(
                anps=[
                    AdminNetworkPolicy(
                        name="pass-everything",
                        priority=1,
                        subject=TierScope(),
                        ingress=[TierRule(action="Pass", peers=[TierScope()])],
                    ),
                    AdminNetworkPolicy(
                        name="shadowed-deny",
                        priority=50,
                        subject=TierScope(),
                        ingress=[TierRule(action="Deny", peers=[TierScope()])],
                    ),
                ],
                banp=BaselineAdminNetworkPolicy(
                    subject=TierScope(namespace_selector=_ns_sel("x")),
                    ingress=[TierRule(action="Deny", peers=[TierScope()])],
                ),
            ),
            cases=list(DEFAULT_TIER_CASES),
        )
    )

    # 4. equal priorities: the (priority, name) total order decides
    out.append(
        TierCase(
            description="overlapping ANP priorities: two priority-5 "
            "policies with conflicting verdicts resolve by name order",
            tags=StringSet.of(TAG_ANP),
            tiers=TierSet(
                anps=[
                    AdminNetworkPolicy(
                        name="a-allow",
                        priority=5,
                        subject=TierScope(namespace_selector=_ns_sel("y")),
                        ingress=[TierRule(action="Allow", peers=[TierScope()])],
                    ),
                    AdminNetworkPolicy(
                        name="b-deny",
                        priority=5,
                        subject=TierScope(namespace_selector=_ns_sel("y")),
                        ingress=[TierRule(action="Deny", peers=[TierScope()])],
                    ),
                ]
            ),
            cases=list(DEFAULT_TIER_CASES),
        )
    )

    # 5. BANP shadowed by NetworkPolicy selection
    out.append(
        TierCase(
            description="BANP default-deny never fires for pods a "
            "NetworkPolicy selects (NP tier is final), and fires for "
            "everything else",
            tags=StringSet.of(TAG_BANP),
            netpols=[
                NetworkPolicy(
                    name="select-xa",
                    namespace="x",
                    spec=NetworkPolicySpec(
                        pod_selector=_pod_sel("a"),
                        policy_types=["Ingress"],
                        ingress=[NetworkPolicyIngressRule()],  # deny-all
                    ),
                )
            ],
            tiers=TierSet(
                banp=BaselineAdminNetworkPolicy(
                    subject=TierScope(),
                    ingress=[
                        TierRule(
                            action="Deny",
                            peers=[TierScope(namespace_selector=_ns_sel("z"))],
                        ),
                        TierRule(action="Allow", peers=[TierScope()]),
                    ],
                )
            ),
            cases=list(DEFAULT_TIER_CASES),
        )
    )

    # 6. endPort ranges through the tier port slabs
    out.append(
        TierCase(
            description="ANP portRange (endPort analog) admits only the "
            "[80, 81] window; 82 stays at the lower tiers",
            tags=StringSet.of(TAG_ANP),
            netpols=[default_deny_netpol("y")],
            tiers=TierSet(
                anps=[
                    AdminNetworkPolicy(
                        name="range-allow",
                        priority=3,
                        subject=TierScope(namespace_selector=_ns_sel("y")),
                        ingress=[
                            TierRule(
                                action="Allow",
                                peers=[TierScope()],
                                ports=[
                                    TierPort(
                                        protocol="TCP",
                                        port=IntOrString(80),
                                        end_port=81,
                                    )
                                ],
                            )
                        ],
                    )
                ]
            ),
            cases=[
                PortCase(80, "serve-80-tcp", "TCP"),
                PortCase(81, "serve-81-tcp", "TCP"),
                PortCase(82, "serve-82-tcp", "TCP"),
            ],
        )
    )

    # 7. SCTP through the full lattice
    out.append(
        TierCase(
            description="SCTP-only ANP Deny: TCP/UDP fall through to "
            "default-allow, SCTP from z is dropped",
            tags=StringSet.of(TAG_ANP, TAG_SCTP),
            tiers=TierSet(
                anps=[
                    AdminNetworkPolicy(
                        name="sctp-deny",
                        priority=9,
                        subject=TierScope(),
                        ingress=[
                            TierRule(
                                action="Deny",
                                peers=[TierScope(namespace_selector=_ns_sel("z"))],
                                ports=[
                                    TierPort(
                                        protocol="SCTP", port=IntOrString(82)
                                    )
                                ],
                            )
                        ],
                    )
                ]
            ),
            cases=list(DEFAULT_TIER_CASES),
        )
    )

    # 8. per-namespace default-deny under a Pass-everything ANP
    out.append(
        TierCase(
            description="per-namespace default-deny in every namespace "
            "under an ANP Pass: the NP tier decides everywhere, BANP "
            "allow never fires",
            tags=StringSet.of(TAG_ANP, TAG_TIER_PASS, TAG_DEFAULT_DENY_NS),
            netpols=per_namespace_default_deny(["x", "y", "z"]),
            tiers=TierSet(
                anps=[
                    AdminNetworkPolicy(
                        name="pass-all",
                        priority=0,
                        subject=TierScope(),
                        ingress=[TierRule(action="Pass", peers=[TierScope()])],
                        egress=[TierRule(action="Pass", peers=[TierScope()])],
                    )
                ],
                banp=BaselineAdminNetworkPolicy(
                    subject=TierScope(),
                    ingress=[TierRule(action="Allow", peers=[TierScope()])],
                ),
            ),
            cases=list(DEFAULT_TIER_CASES),
        )
    )

    # 9. egress lattice: ANP egress Deny + BANP egress Allow
    out.append(
        TierCase(
            description="egress direction: ANP denies x->z egress, BANP "
            "allows the rest of x's egress explicitly",
            tags=StringSet.of(TAG_ANP, TAG_BANP),
            netpols=[
                NetworkPolicy(
                    name="x-egress-to-y",
                    namespace="x",
                    spec=NetworkPolicySpec(
                        pod_selector=_pod_sel("b"),
                        policy_types=["Egress"],
                        egress=[
                            NetworkPolicyEgressRule(
                                to=[
                                    NetworkPolicyPeer(
                                        namespace_selector=_ns_sel("y")
                                    )
                                ],
                                ports=[
                                    NetworkPolicyPort(
                                        protocol="UDP", port=IntOrString(81)
                                    )
                                ],
                            )
                        ],
                    ),
                )
            ],
            tiers=TierSet(
                anps=[
                    AdminNetworkPolicy(
                        name="deny-x-to-z",
                        priority=4,
                        subject=TierScope(namespace_selector=_ns_sel("x")),
                        egress=[
                            TierRule(
                                action="Deny",
                                peers=[TierScope(namespace_selector=_ns_sel("z"))],
                            )
                        ],
                    )
                ],
                banp=BaselineAdminNetworkPolicy(
                    subject=TierScope(namespace_selector=_ns_sel("x")),
                    egress=[TierRule(action="Allow", peers=[TierScope()])],
                ),
            ),
            cases=list(DEFAULT_TIER_CASES),
        )
    )

    # 10. empty-selector subject + pods-variant peer + named port
    out.append(
        TierCase(
            description="pods-variant scopes: subject {all-ns, pod=c} "
            "denied from peer {ns=y, pod=a} on the named port only",
            tags=StringSet.of(TAG_ANP),
            tiers=TierSet(
                anps=[
                    AdminNetworkPolicy(
                        name="named-port-deny",
                        priority=2,
                        subject=TierScope(
                            namespace_selector=LabelSelector.make(),
                            pod_selector=_pod_sel("c"),
                        ),
                        ingress=[
                            TierRule(
                                action="Deny",
                                peers=[
                                    TierScope(
                                        namespace_selector=_ns_sel("y"),
                                        pod_selector=_pod_sel("a"),
                                    )
                                ],
                                ports=[
                                    TierPort(
                                        protocol="TCP",
                                        port=IntOrString("serve-80-tcp"),
                                    )
                                ],
                            )
                        ],
                    )
                ]
            ),
            cases=list(DEFAULT_TIER_CASES),
        )
    )

    return out
