"""TestCase / TestStep DSL + per-case feature extraction
(reference: generator/testcase.go)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List

from ..kube.netpol import NetworkPolicy
from ..probe.probeconfig import ProbeConfig
from .actions import Action
from .features import (
    ACTION_FEATURE_CREATE_NAMESPACE,
    ACTION_FEATURE_CREATE_POD,
    ACTION_FEATURE_CREATE_POLICY,
    ACTION_FEATURE_DELETE_NAMESPACE,
    ACTION_FEATURE_DELETE_POD,
    ACTION_FEATURE_DELETE_POLICY,
    ACTION_FEATURE_READ_POLICIES,
    ACTION_FEATURE_SET_NAMESPACE_LABELS,
    ACTION_FEATURE_SET_POD_LABELS,
    ACTION_FEATURE_UPDATE_POLICY,
    EGRESS_TRAVERSER,
    GENERAL_TRAVERSER,
    INGRESS_TRAVERSER,
)
from .tags import StringSet


@dataclass
class TestStep:
    __test__ = False  # not a pytest class
    probe: ProbeConfig
    actions: List[Action] = field(default_factory=list)


@dataclass
class TestCase:
    __test__ = False  # not a pytest class
    description: str
    tags: StringSet
    steps: List[TestStep]

    def collect_actions_and_policies(self):
        """testcase.go:39-73."""
        features: Dict[str, bool] = {}
        policies: List[NetworkPolicy] = []
        for step in self.steps:
            for action in step.actions:
                if action.create_policy is not None:
                    features[ACTION_FEATURE_CREATE_POLICY] = True
                    policies.append(action.create_policy.policy)
                elif action.update_policy is not None:
                    features[ACTION_FEATURE_UPDATE_POLICY] = True
                    policies.append(action.update_policy.policy)
                elif action.delete_policy is not None:
                    features[ACTION_FEATURE_DELETE_POLICY] = True
                elif action.create_namespace is not None:
                    features[ACTION_FEATURE_CREATE_NAMESPACE] = True
                elif action.set_namespace_labels is not None:
                    features[ACTION_FEATURE_SET_NAMESPACE_LABELS] = True
                elif action.delete_namespace is not None:
                    features[ACTION_FEATURE_DELETE_NAMESPACE] = True
                elif action.read_network_policies is not None:
                    features[ACTION_FEATURE_READ_POLICIES] = True
                elif action.create_pod is not None:
                    features[ACTION_FEATURE_CREATE_POD] = True
                elif action.set_pod_labels is not None:
                    features[ACTION_FEATURE_SET_POD_LABELS] = True
                elif action.delete_pod is not None:
                    features[ACTION_FEATURE_DELETE_POD] = True
                else:
                    raise ValueError("invalid Action")
        return features, policies

    def get_features(self) -> Dict[str, List[str]]:
        """testcase.go:75-90."""
        from .netpol_builder import Netpol

        action_set, policies = self.collect_actions_and_policies()
        general, ingress, egress = {}, {}, {}
        for policy in policies:
            parsed = Netpol.from_network_policy(policy)
            general.update(GENERAL_TRAVERSER.traverse(parsed))
            ingress.update(INGRESS_TRAVERSER.traverse(parsed))
            egress.update(EGRESS_TRAVERSER.traverse(parsed))
        return {
            "general": sorted(general),
            "ingress": sorted(ingress),
            "egress": sorted(egress),
            "action": sorted(action_set),
        }


def new_single_step_test_case(
    description: str, tags: StringSet, probe: ProbeConfig, *actions: Action
) -> TestCase:
    """testcase.go:18-29: empty description falls back to sorted tags."""
    if not description:
        description = ",".join(tags.keys_sorted())
    return TestCase(
        description=description,
        tags=tags,
        steps=[TestStep(probe=probe, actions=list(actions))],
    )


def new_test_case(description: str, tags: StringSet, *steps: TestStep) -> TestCase:
    return TestCase(description=description, tags=tags, steps=list(steps))
