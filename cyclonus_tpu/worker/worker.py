"""The in-pod worker side (reference: worker/worker.go): parse a JSON
batch, issue the probes concurrently, print JSON results."""

from __future__ import annotations

import json
import os
import subprocess
import time
from concurrent.futures import ThreadPoolExecutor
from typing import List

from ..telemetry import events, instruments as ti
from ..telemetry.spans import adopt, current_path, span
from .model import Batch, Request, Result

DEFAULT_CONCURRENCY = 10
RETRIES = 1


def _issue_one(request: Request) -> Result:
    """Issue one probe (with retries), stamping per-probe wall-clock into
    Result.latency_ms — the real-probe latency histogram's data source —
    and the worker-side telemetry histogram."""
    t0 = time.perf_counter()
    result = _probe_with_retries(request)
    dt = time.perf_counter() - t0
    result.latency_ms = round(dt * 1000.0, 3)
    ti.PROBE_LATENCY.observe(
        dt,
        source="worker",
        outcome="ok" if result.is_success() else "error",
    )
    return result


def _probe_with_retries(request: Request) -> Result:
    """worker.go:60-84 with one retry (worker.go:62-68).

    CYCLONUS_CONNECT_NATIVE=1 probes with python sockets instead of
    shelling to /agnhost — the loopback cluster's mode (kube/loopback.py),
    where the worker runs as a real subprocess on a machine without the
    agnhost binary and binds CYCLONUS_SOURCE_IP so the destination pod
    server sees the probing pod's address."""
    if os.environ.get("CYCLONUS_CONNECT_NATIVE") == "1":
        from ..kube.loopback import native_probe

        last_err = ""
        for _attempt in range(1 + RETRIES):
            err = native_probe(
                request.host,
                request.port,
                request.protocol,
                source_ip=os.environ.get("CYCLONUS_SOURCE_IP") or None,
            )
            if err is None:
                return Result(request=request, output="connected")
            last_err = err
        return Result(request=request, output="", error=last_err)
    command = request.command()
    last_err = ""
    out = ""
    for _attempt in range(1 + RETRIES):
        try:
            proc = subprocess.run(
                command, capture_output=True, text=True, timeout=5
            )
            out = proc.stdout
            if proc.returncode == 0:
                return Result(request=request, output=out)
            last_err = proc.stderr.strip() or f"exit code {proc.returncode}"
        except FileNotFoundError as e:
            last_err = str(e)
        except subprocess.TimeoutExpired:
            last_err = "timeout"
    return Result(request=request, output=out, error=last_err)


def issue_batch(batch: Batch, concurrency: int = DEFAULT_CONCURRENCY) -> List[Result]:
    """worker.go:38-58.

    With trace context on the batch (model.py Batch.trace_id), the
    worker joins the driver's trace: a worker.batch span adopted under
    the driver's span path, one worker.probe span per request (the pool
    threads re-adopt the batch path — pool.map drops thread-locals)."""
    if not batch.requests:
        return []
    if batch.trace_id and not (
        events.enabled() and events.trace_id() == batch.trace_id
    ):
        # a REAL worker process joins the driver's trace as itself; an
        # IN-PROCESS worker (tests, --mock) is already recording on this
        # trace and must not flip the process-global role to "worker" —
        # that would mislabel every later driver-side event
        events.enable(batch.trace_id, role="worker")
    if not events.enabled():
        with ThreadPoolExecutor(max_workers=concurrency) as pool:
            return list(pool.map(_issue_one, batch.requests))
    # span-recording path: driver-supplied context (batch.trace_id), or
    # a locally enabled trace (worker --trace-out standalone debugging)
    with adopt(batch.parent_span):
        with span("worker.batch", pod=batch.key(), requests=len(batch.requests)):
            batch_path = current_path()

            def traced(request: Request) -> Result:
                with adopt(batch_path):
                    with span(
                        "worker.probe",
                        key=request.key,
                        host=request.host,
                        port=request.port,
                        protocol=request.protocol,
                    ):
                        return _issue_one(request)

            with ThreadPoolExecutor(max_workers=concurrency) as pool:
                return list(pool.map(traced, batch.requests))


def _attach_trace_events(
    batch: Batch, results: List[Result], evts: List[dict]
) -> None:
    """Distribute the worker's recorded events onto the Results for the
    trip back to the driver (model.py Result.trace_events, optional on
    the wire): each probe span rides its own request's Result (matched
    by the span's key attr); batch-level spans ride the first Result."""
    evts = [e for e in evts if e.get("trace_id") == batch.trace_id]
    if not evts or not results:
        return
    by_key: dict = {}
    batch_level: List[dict] = []
    for e in evts:
        key = (e.get("args") or {}).get("key")
        (by_key.setdefault(key, []) if key else batch_level).append(e)
    for r in results:
        r.trace_events = by_key.get(r.request.key) or None
    if batch_level:
        results[0].trace_events = batch_level + (results[0].trace_events or [])


def run_worker(jobs_json: str) -> str:
    """worker.go:18-36: JSON in, JSON out."""
    batch = Batch.from_json(jobs_json)
    marker = events.mark()
    results = issue_batch(batch)
    if batch.trace_id:
        _attach_trace_events(batch, results, events.since(marker))
    return json.dumps([r.to_dict() for r in results])
