"""The in-pod worker side (reference: worker/worker.go): parse a JSON
batch, issue the probes concurrently, print JSON results."""

from __future__ import annotations

import json
import os
import subprocess
import time
from concurrent.futures import ThreadPoolExecutor
from typing import List

from ..telemetry import instruments as ti
from .model import Batch, Request, Result

DEFAULT_CONCURRENCY = 10
RETRIES = 1


def _issue_one(request: Request) -> Result:
    """Issue one probe (with retries), stamping per-probe wall-clock into
    Result.latency_ms — the real-probe latency histogram's data source —
    and the worker-side telemetry histogram."""
    t0 = time.perf_counter()
    result = _probe_with_retries(request)
    dt = time.perf_counter() - t0
    result.latency_ms = round(dt * 1000.0, 3)
    ti.PROBE_LATENCY.observe(
        dt,
        source="worker",
        outcome="ok" if result.is_success() else "error",
    )
    return result


def _probe_with_retries(request: Request) -> Result:
    """worker.go:60-84 with one retry (worker.go:62-68).

    CYCLONUS_CONNECT_NATIVE=1 probes with python sockets instead of
    shelling to /agnhost — the loopback cluster's mode (kube/loopback.py),
    where the worker runs as a real subprocess on a machine without the
    agnhost binary and binds CYCLONUS_SOURCE_IP so the destination pod
    server sees the probing pod's address."""
    if os.environ.get("CYCLONUS_CONNECT_NATIVE") == "1":
        from ..kube.loopback import native_probe

        last_err = ""
        for _attempt in range(1 + RETRIES):
            err = native_probe(
                request.host,
                request.port,
                request.protocol,
                source_ip=os.environ.get("CYCLONUS_SOURCE_IP") or None,
            )
            if err is None:
                return Result(request=request, output="connected")
            last_err = err
        return Result(request=request, output="", error=last_err)
    command = request.command()
    last_err = ""
    out = ""
    for _attempt in range(1 + RETRIES):
        try:
            proc = subprocess.run(
                command, capture_output=True, text=True, timeout=5
            )
            out = proc.stdout
            if proc.returncode == 0:
                return Result(request=request, output=out)
            last_err = proc.stderr.strip() or f"exit code {proc.returncode}"
        except FileNotFoundError as e:
            last_err = str(e)
        except subprocess.TimeoutExpired:
            last_err = "timeout"
    return Result(request=request, output=out, error=last_err)


def issue_batch(batch: Batch, concurrency: int = DEFAULT_CONCURRENCY) -> List[Result]:
    """worker.go:38-58."""
    if not batch.requests:
        return []
    with ThreadPoolExecutor(max_workers=concurrency) as pool:
        return list(pool.map(_issue_one, batch.requests))


def run_worker(jobs_json: str) -> str:
    """worker.go:18-36: JSON in, JSON out."""
    batch = Batch.from_json(jobs_json)
    results = issue_batch(batch)
    return json.dumps([r.to_dict() for r in results])
