"""Batch/Request/Result wire model (reference: worker/model.go)."""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class Request:
    """model.go:26-48."""

    key: str
    protocol: str
    host: str
    port: int

    def command(self) -> List[str]:
        """The agnhost connect invocation (model.go:50-61)."""
        proto = self.protocol.lower()
        if proto not in ("tcp", "udp", "sctp"):
            raise ValueError(f"invalid protocol {self.protocol}")
        return [
            "/agnhost",
            "connect",
            f"{self.host}:{self.port}",
            "--timeout=1s",
            f"--protocol={proto}",
        ]

    def to_dict(self) -> dict:
        return {
            "Key": self.key,
            "Protocol": self.protocol,
            "Host": self.host,
            "Port": self.port,
        }

    @staticmethod
    def from_dict(d: dict) -> "Request":
        return Request(
            key=d["Key"], protocol=d["Protocol"], host=d["Host"], port=d["Port"]
        )


@dataclass
class Batch:
    """model.go:9-24."""

    namespace: str
    pod: str
    container: str
    requests: List[Request] = field(default_factory=list)

    def key(self) -> str:
        return f"{self.namespace}/{self.pod}/{self.container}"

    def to_json(self) -> str:
        return json.dumps(
            {
                "Namespace": self.namespace,
                "Pod": self.pod,
                "Container": self.container,
                "Requests": [r.to_dict() for r in self.requests],
            }
        )

    @staticmethod
    def from_json(text: str) -> "Batch":
        d = json.loads(text)
        return Batch(
            namespace=d.get("Namespace", ""),
            pod=d.get("Pod", ""),
            container=d.get("Container", ""),
            requests=[Request.from_dict(r) for r in d.get("Requests") or []],
        )


@dataclass
class Result:
    """model.go:50-61.

    latency_ms is new vs the reference: per-probe wall-clock measured by
    the worker (worker.py _issue_one), the data source for the driver's
    real-probe latency histogram.  It is OPTIONAL on the wire in both
    directions — old workers omit it, old drivers ignore the extra key —
    so the JSON stays backward-compatible."""

    request: Request
    output: str = ""
    error: str = ""
    latency_ms: Optional[float] = None

    def is_success(self) -> bool:
        return self.error == ""

    def to_dict(self) -> dict:
        d = {
            "Request": self.request.to_dict(),
            "Output": self.output,
            "Error": self.error,
        }
        if self.latency_ms is not None:
            d["LatencyMs"] = self.latency_ms
        return d

    @staticmethod
    def from_dict(d: dict) -> "Result":
        latency = d.get("LatencyMs")
        return Result(
            request=Request.from_dict(d["Request"]),
            output=d.get("Output", ""),
            error=d.get("Error", ""),
            latency_ms=float(latency) if latency is not None else None,
        )
