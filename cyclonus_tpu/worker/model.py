"""Batch/Request/Result wire model (reference: worker/model.go).

Wire-protocol compatibility rules (the full versioned declaration —
key types, optionality, version rows, emit guards — lives in
worker/wireregistry.py; tools/wirelint.py verifies this module against
it statically, and tests/skewharness.py replays every version-skew
pair dynamically):

  * The reference shape (Namespace/Pod/Container/Requests; Request/
    Output/Error) is frozen: those keys are always emitted, so an old
    (even Go) consumer keeps parsing.
  * Every extension is an OPTIONAL field: serialization omits it when
    unset (`to_dict`/`to_json` emit no key), and parsing treats a
    missing key as the unset default (`.get`).  Old workers simply never
    emit it; old drivers never look for it.
  * Unknown keys are TOLERATED on parse: `from_dict`/`from_json` read
    the keys they know and ignore the rest, so a NEWER peer's extra
    fields never break an older one.
  * Evolution is additive-optional ONLY: which version introduced each
    key is pinned by worker/wire_schema.json (the committed golden);
    changing the protocol = adding a registry row and regenerating the
    golden (`python -m cyclonus_tpu.worker.wireregistry
    --write-golden`), never editing a shipped key.

Each class's ``WIRE`` table is DERIVED from the registry
(`wireregistry.wire_table`), so a key declared there is covered by
emit-check, reader-check, the skew views, and the frozen schema
automatically.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, ClassVar, Dict, List, Optional

from ..utils import contracts
from . import wireregistry


@dataclass
class Request:
    """model.go:26-48."""

    # Wire dtype contract, derived from the one declaration in
    # wireregistry.MESSAGES (tools/wirelint.py checks emit/read sites
    # statically; contracts.check_wire validates real payloads under
    # CYCLONUS_SHAPE_CHECK=1).
    WIRE: ClassVar[Dict[str, contracts.WireField]] = (
        wireregistry.wire_table("Request")
    )

    key: str
    protocol: str
    host: str
    port: int

    def command(self) -> List[str]:
        """The agnhost connect invocation (model.go:50-61)."""
        proto = self.protocol.lower()
        if proto not in ("tcp", "udp", "sctp"):
            raise ValueError(f"invalid protocol {self.protocol}")
        return [
            "/agnhost",
            "connect",
            f"{self.host}:{self.port}",
            "--timeout=1s",
            f"--protocol={proto}",
        ]

    def to_dict(self) -> dict:
        d = {
            "Key": self.key,
            "Protocol": self.protocol,
            "Host": self.host,
            "Port": self.port,
        }
        if contracts.CHECK:
            contracts.check_wire("Request", d, self.WIRE)
        return d

    @staticmethod
    def from_dict(d: dict) -> "Request":
        if contracts.CHECK:
            contracts.check_wire("Request", d, Request.WIRE)
        return Request(
            key=d["Key"], protocol=d["Protocol"], host=d["Host"], port=d["Port"]
        )


@dataclass
class Delta:
    """One cluster-state mutation for the verdict service
    (cyclonus_tpu/serve): pod add/remove, pod or namespace label change,
    policy create/update/delete.  `kind` selects which optional payload
    keys are meaningful; unused ones stay unset (omitted on the wire).

    KINDS is one half of a lifecycle contract: every member must carry
    a KindSpec row in serve/stateregistry.py (validate -> apply ->
    rollback -> named gate) and vice versa — statelint ST005 and
    test_worker's registry cross-check both fail on drift."""

    KINDS: ClassVar[tuple] = (
        "pod_add",       # Namespace/Name + Labels + Ip
        "pod_remove",    # Namespace/Name
        "pod_labels",    # Namespace/Name + Labels (full replacement)
        "ns_labels",     # Namespace + Labels (full replacement)
        "policy_upsert", # Namespace/Name + Policy (NetworkPolicy dict)
        "policy_delete", # Namespace/Name
        # precedence-tier objects (cyclonus_tpu/tiers): cluster-scoped,
        # so Namespace stays empty; the k8s-shaped ANP/BANP dict rides
        # the SAME optional Policy key — new kinds are data values, not
        # new wire keys, so the envelope is unchanged and an old peer
        # rejects them at validation, never at parse
        "anp_upsert",    # Name + Policy (AdminNetworkPolicy dict)
        "anp_delete",    # Name
        "banp_upsert",   # Policy (BaselineAdminNetworkPolicy dict)
        "banp_delete",   #
    )

    WIRE: ClassVar[Dict[str, contracts.WireField]] = (
        wireregistry.wire_table("Delta")
    )

    kind: str
    namespace: str = ""  # empty for the cluster-scoped tier kinds
    name: str = ""
    labels: Optional[Dict[str, str]] = None
    ip: Optional[str] = None
    policy: Optional[Dict[str, Any]] = None

    def to_dict(self) -> dict:
        d: Dict[str, Any] = {"Kind": self.kind, "Namespace": self.namespace}
        if self.name:
            d["Name"] = self.name
        if self.labels is not None:
            d["Labels"] = dict(self.labels)
        if self.ip is not None:
            d["Ip"] = self.ip
        if self.policy is not None:
            d["Policy"] = dict(self.policy)
        if contracts.CHECK:
            contracts.check_wire("Delta", d, self.WIRE)
        return d

    @staticmethod
    def from_dict(d: dict) -> "Delta":
        if contracts.CHECK:
            contracts.check_wire_read("Delta", d, Delta.WIRE)
        labels = d.get("Labels")
        policy = d.get("Policy")
        return Delta(
            kind=d.get("Kind", ""),
            namespace=d.get("Namespace", ""),
            name=d.get("Name", "") or "",
            labels=dict(labels) if labels is not None else None,
            ip=d.get("Ip"),
            policy=dict(policy) if policy is not None else None,
        )


@dataclass
class FlowQuery:
    """One "is this flow allowed" question for the verdict service:
    src/dst are pod keys ("namespace/name") known to the serving engine;
    the (port, port_name, protocol) triple resolves exactly like an
    engine PortCase."""

    WIRE: ClassVar[Dict[str, contracts.WireField]] = (
        wireregistry.wire_table("FlowQuery")
    )

    src: str
    dst: str
    port: int
    protocol: str
    port_name: str = ""

    def to_dict(self) -> dict:
        d: Dict[str, Any] = {
            "Src": self.src,
            "Dst": self.dst,
            "Port": self.port,
            "Protocol": self.protocol,
        }
        if self.port_name:
            d["PortName"] = self.port_name
        if contracts.CHECK:
            contracts.check_wire("FlowQuery", d, self.WIRE)
        return d

    @staticmethod
    def from_dict(d: dict) -> "FlowQuery":
        if contracts.CHECK:
            contracts.check_wire_read("FlowQuery", d, FlowQuery.WIRE)
        return FlowQuery(
            src=d.get("Src", ""),
            dst=d.get("Dst", ""),
            port=int(d.get("Port", 0)),
            protocol=d.get("Protocol", ""),
            port_name=d.get("PortName", "") or "",
        )


@dataclass
class Verdict:
    """The verdict service's answer to one FlowQuery: the query echoed
    back (responses may be reordered relative to a batch), the three
    allow bits, and the engine epoch the answer was computed at (the
    staleness anchor).  A query the engine cannot answer (unknown pod
    key, bad protocol) carries Error and all-False bits.

    Shed is the SLO engine's typed refusal (optional, omitted when
    False — pre-SLO peers never see it): the service declined to
    answer because the query-latency error budget was exhausted.  A
    shed verdict also carries Error, so a caller that predates the
    field still treats it as a non-answer rather than reading the
    all-False bits as a deny."""

    WIRE: ClassVar[Dict[str, contracts.WireField]] = (
        wireregistry.wire_table("Verdict")
    )

    query: FlowQuery
    ingress: bool = False
    egress: bool = False
    combined: bool = False
    epoch: Optional[int] = None
    error: str = ""
    latency_ms: Optional[float] = None
    shed: bool = False

    def to_dict(self) -> dict:
        d: Dict[str, Any] = {
            "Query": self.query.to_dict(),
            "Ingress": self.ingress,
            "Egress": self.egress,
            "Combined": self.combined,
        }
        if self.epoch is not None:
            d["Epoch"] = self.epoch
        if self.error:
            d["Error"] = self.error
        if self.latency_ms is not None:
            d["LatencyMs"] = self.latency_ms
        if self.shed:
            d["Shed"] = True
        if contracts.CHECK:
            contracts.check_wire("Verdict", d, self.WIRE)
        return d

    @staticmethod
    def from_dict(d: dict) -> "Verdict":
        if contracts.CHECK:
            contracts.check_wire_read("Verdict", d, Verdict.WIRE)
        latency = d.get("LatencyMs")
        return Verdict(
            query=FlowQuery.from_dict(d.get("Query") or {}),
            ingress=bool(d.get("Ingress", False)),
            egress=bool(d.get("Egress", False)),
            combined=bool(d.get("Combined", False)),
            epoch=d.get("Epoch"),
            error=d.get("Error", "") or "",
            latency_ms=float(latency) if latency is not None else None,
            shed=bool(d.get("Shed", False)),
        )


@dataclass
class Batch:
    """model.go:9-24.

    trace_id / parent_span are OPTIONAL trace context (see the module
    docstring's compatibility rules): when the driver is recording a
    timeline, it stamps its trace id and current span path here so the
    worker's spans join the same trace, nested under the issuing step.

    deltas / queries are the OPTIONAL verdict-service payloads: a serve
    batch rides the same envelope as a probe batch (Namespace/Pod/
    Container may be empty there — the service is not pod-scoped), so
    one stream can carry probes to workers and deltas/queries to the
    service without a second protocol."""

    WIRE: ClassVar[Dict[str, contracts.WireField]] = (
        wireregistry.wire_table("Batch")
    )

    namespace: str
    pod: str
    container: str
    requests: List[Request] = field(default_factory=list)
    trace_id: str = ""
    parent_span: str = ""
    deltas: List[Delta] = field(default_factory=list)
    queries: List[FlowQuery] = field(default_factory=list)

    def key(self) -> str:
        return f"{self.namespace}/{self.pod}/{self.container}"

    def to_json(self) -> str:
        d: Dict[str, Any] = {
            "Namespace": self.namespace,
            "Pod": self.pod,
            "Container": self.container,
            "Requests": [r.to_dict() for r in self.requests],
        }
        if self.trace_id:
            d["TraceId"] = self.trace_id
            if self.parent_span:
                d["ParentSpan"] = self.parent_span
        if self.deltas:
            d["Deltas"] = [x.to_dict() for x in self.deltas]
        if self.queries:
            d["Queries"] = [x.to_dict() for x in self.queries]
        if contracts.CHECK:
            contracts.check_wire("Batch", d, self.WIRE)
        return json.dumps(d)

    @staticmethod
    def from_json(text: str) -> "Batch":
        d = json.loads(text)
        # tolerant parse on purpose (module docstring): missing required
        # keys default rather than raise — but a payload that isn't an
        # object, or a present key with a drifted type, is a peer wire
        # break and gets rejected with the offending key named
        if contracts.CHECK:
            contracts.check_wire_read("Batch", d, Batch.WIRE)
        return Batch(
            namespace=d.get("Namespace", ""),
            pod=d.get("Pod", ""),
            container=d.get("Container", ""),
            requests=[Request.from_dict(r) for r in d.get("Requests") or []],
            trace_id=d.get("TraceId", "") or "",
            parent_span=d.get("ParentSpan", "") or "",
            deltas=[Delta.from_dict(x) for x in d.get("Deltas") or []],
            queries=[FlowQuery.from_dict(x) for x in d.get("Queries") or []],
        )


@dataclass
class Result:
    """model.go:50-61.

    latency_ms and trace_events are optional extensions (module
    docstring): per-probe wall-clock measured by the worker
    (worker.py _issue_one) feeding the driver's real-probe latency
    histogram, and the worker's recorded trace events riding back for
    the merged driver+worker timeline."""

    WIRE: ClassVar[Dict[str, contracts.WireField]] = (
        wireregistry.wire_table("Result")
    )

    request: Request
    output: str = ""
    error: str = ""
    latency_ms: Optional[float] = None
    trace_events: Optional[List[Dict[str, Any]]] = None

    def is_success(self) -> bool:
        return self.error == ""

    def to_dict(self) -> dict:
        d: Dict[str, Any] = {
            "Request": self.request.to_dict(),
            "Output": self.output,
            "Error": self.error,
        }
        if self.latency_ms is not None:
            d["LatencyMs"] = self.latency_ms
        if self.trace_events:
            d["TraceEvents"] = self.trace_events
        if contracts.CHECK:
            contracts.check_wire("Result", d, self.WIRE)
        return d

    @staticmethod
    def from_dict(d: dict) -> "Result":
        # parse side is tolerant of ABSENT keys (old peers), but a
        # present key with a drifted type is a wire break worth catching
        if contracts.CHECK:
            contracts.check_wire_read("Result", d, Result.WIRE)
        latency = d.get("LatencyMs")
        events = d.get("TraceEvents")
        return Result(
            request=Request.from_dict(d["Request"]),
            output=d.get("Output", ""),
            error=d.get("Error", ""),
            latency_ms=float(latency) if latency is not None else None,
            trace_events=list(events) if events else None,
        )


#: The real (parse, emit) pair for each registered message this module
#: models — what wireregistry.skew_sweep drives every synthesized skew
#: view through, so the compat proof exercises THESE codecs, not a
#: test-only re-implementation.  (The Reply envelope has no class; the
#: sweep falls back to the registry-generic codec for it.)
CODECS: Dict[str, Any] = {
    "Request": (Request.from_dict, lambda r: r.to_dict()),
    "Batch": (
        lambda d: Batch.from_json(json.dumps(d)),
        lambda b: json.loads(b.to_json()),
    ),
    "Result": (Result.from_dict, lambda r: r.to_dict()),
    "Delta": (Delta.from_dict, lambda x: x.to_dict()),
    "FlowQuery": (FlowQuery.from_dict, lambda q: q.to_dict()),
    "Verdict": (Verdict.from_dict, lambda v: v.to_dict()),
}
