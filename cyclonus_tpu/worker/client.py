"""Driver-side worker client (reference: worker/client.go): marshal a
batch, kubectl-exec the in-pod worker, parse its stdout."""

from __future__ import annotations

import json
from typing import List

from ..kube.ikubernetes import IKubernetes, KubeError
from .model import Batch, Result


class Client:
    """Stateless per-call by design (lock discipline, docs/DESIGN.md):
    probe runners issue batches from a thread pool, so the client holds
    no mutable state of its own — the only shared structure the batch
    path touches is the trace-event ring, whose BoundedRing lock (and
    pid-dedup in events.ingest) makes concurrent ingestion safe.
    tests/raceharness.py `worker_ingest` fuzzes exactly this path."""

    def __init__(self, kubernetes: IKubernetes):
        self.kubernetes = kubernetes

    def batch(self, batch: Batch) -> List[Result]:
        """client.go:14-41."""
        command = ["/worker", "--jobs", batch.to_json()]
        stdout, _stderr, command_err = self.kubernetes.execute_remote_command(
            batch.namespace, batch.pod, batch.container, command
        )
        if command_err is not None:
            raise KubeError(f"worker exec failed: {command_err}")
        try:
            parsed = json.loads(stdout) if stdout.strip() else []
        except json.JSONDecodeError as e:
            raise KubeError(f"unable to parse worker output: {e}")
        results = [Result.from_dict(d) for d in parsed]
        if batch.trace_id:
            # merge the worker's recorded events into the driver's
            # timeline (in-process workers are deduped by pid in ingest)
            from ..telemetry import events

            for r in results:
                if r.trace_events:
                    events.ingest(r.trace_events)
        return results
