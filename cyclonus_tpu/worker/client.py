"""Driver-side worker client (reference: worker/client.go): marshal a
batch, kubectl-exec the in-pod worker, parse its stdout.

Wire robustness (docs/DESIGN.md "Cold start & chaos"): each batch issue
is BOUNDED (CYCLONUS_WORKER_TIMEOUT_S; a worker pod that dies mid-exec
must cost a timeout, never a wedged driver thread) and RETRIED with the
one canonical full-jitter backoff (utils/retry.py — the same envelope
the backend-init and tunnel probes use), CYCLONUS_WORKER_RETRIES extra
attempts.  Probes are idempotent connection attempts, so a re-issued
batch re-measures, it never double-commits.  Every retry counts into
cyclonus_tpu_worker_retries_total; the final failure raises KubeError
carrying the last error.  The chaos layer's `worker_wire` /
`worker_wire_stall` points inject exactly these fault classes.
"""

from __future__ import annotations

import json
import os
import random
from typing import List

from .. import chaos
from ..kube.ikubernetes import IKubernetes, KubeError
from ..utils import contracts
from ..telemetry import instruments as ti
from ..utils.bounded import run_bounded
from ..utils.retry import full_jitter_pause
from .model import Batch, Result


def _timeout_s() -> float:
    """Per-batch wall-clock bound; <= 0 disables the bound (the exec
    call then blocks as long as kubectl does)."""
    try:
        return float(os.environ.get("CYCLONUS_WORKER_TIMEOUT_S", "120"))
    except ValueError:
        return 120.0


def _retries() -> int:
    try:
        return max(0, int(os.environ.get("CYCLONUS_WORKER_RETRIES", "2")))
    except ValueError:
        return 2


def _backoff_s() -> float:
    try:
        return float(os.environ.get("CYCLONUS_WORKER_BACKOFF_S", "0.5"))
    except ValueError:
        return 0.5


class Client:
    """Stateless per-call by design (lock discipline, docs/DESIGN.md):
    probe runners issue batches from a thread pool, so the client holds
    no mutable state of its own — the only shared structure the batch
    path touches is the trace-event ring, whose BoundedRing lock (and
    pid-dedup in events.ingest) makes concurrent ingestion safe.
    tests/raceharness.py `worker_ingest` fuzzes exactly this path."""

    def __init__(self, kubernetes: IKubernetes):
        self.kubernetes = kubernetes

    def _issue_once(self, batch: Batch) -> List[Result]:
        """client.go:14-41: one exec + parse attempt."""
        chaos.fire("worker_wire")
        chaos.stall("worker_wire_stall")
        command = ["/worker", "--jobs", batch.to_json()]
        stdout, _stderr, command_err = self.kubernetes.execute_remote_command(
            batch.namespace, batch.pod, batch.container, command
        )
        if command_err is not None:
            raise KubeError(f"worker exec failed: {command_err}")
        try:
            parsed = json.loads(stdout) if stdout.strip() else []
        except json.JSONDecodeError as e:
            raise KubeError(f"unable to parse worker output: {e}")
        # reader-side wire validation (CYCLONUS_SHAPE_CHECK=1): a
        # malformed peer reply is rejected here with the offending key
        # named, instead of surfacing as a KeyError deep in from_dict
        if contracts.CHECK:
            if not isinstance(parsed, list):
                raise contracts.ContractViolation(
                    "worker reply: expected a JSON array of Result "
                    f"objects, got {type(parsed).__name__}"
                )
            for d in parsed:  # wire-read: Result
                contracts.check_wire_read("Result", d, Result.WIRE)
        return [Result.from_dict(d) for d in parsed]

    def batch(self, batch: Batch) -> List[Result]:
        """Issue one batch with the timeout + jittered-backoff retry
        envelope; trace events ingest from the SUCCESSFUL attempt only
        (a half-dead attempt's events would duplicate the retry's)."""
        timeout = _timeout_s()
        attempts = _retries() + 1
        rng = random.Random()  # jitter must differ across drivers
        last_error: Exception = KubeError("worker batch never attempted")
        for attempt in range(1, attempts + 1):
            if timeout > 0:
                status, value = run_bounded(
                    lambda: self._issue_once(batch), timeout
                )
                if status == "ok":
                    results = value
                    break
                last_error = (
                    value
                    if status == "error"
                    else KubeError(
                        f"worker batch timed out after {timeout:g}s "
                        "(CYCLONUS_WORKER_TIMEOUT_S)"
                    )
                )
            else:
                try:
                    results = self._issue_once(batch)
                    break
                except Exception as e:
                    last_error = e
            if attempt < attempts:
                ti.WORKER_RETRIES.inc()
                import time as _time

                _time.sleep(full_jitter_pause(_backoff_s(), attempt, rng))
        else:
            raise KubeError(
                f"worker batch failed after {attempts} attempt(s): "
                f"{type(last_error).__name__}: {last_error}"
            )
        if batch.trace_id:
            # merge the worker's recorded events into the driver's
            # timeline (in-process workers are deduped by pid in ingest)
            from ..telemetry import events

            for r in results:
                if r.trace_events:
                    events.ingest(r.trace_events)
        return results
