"""In-pod batch prober (reference: pkg/worker): avoids an apiserver exec
storm by issuing ONE kubectl-exec per source pod carrying a JSON batch of
probe requests; the in-pod worker fans out with a thread pool and returns
JSON results on stdout."""

from .model import Batch, Request, Result
from .client import Client
from .worker import run_worker, issue_batch

__all__ = ["Batch", "Request", "Result", "Client", "run_worker", "issue_batch"]
