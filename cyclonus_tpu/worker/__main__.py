"""In-pod worker entrypoint (reference: cmd/worker/main.go + worker/cli.go):
`python -m cyclonus_tpu.worker --jobs '<batch json>'` issues the batch's
probes and prints JSON results on stdout (the driver-side Client parses
them from the kubectl-exec stream)."""

from __future__ import annotations

import argparse
import sys

from .worker import run_worker


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="cyclonus-worker", description="in-pod batch connectivity prober"
    )
    parser.add_argument(
        "--jobs", required=True, help="JSON-serialized worker Batch"
    )
    parser.add_argument(
        "--metrics-port",
        type=int,
        default=None,
        metavar="PORT",
        help="serve Prometheus /metrics on 127.0.0.1:PORT (0 = ephemeral) "
        "for the batch's duration — per-probe latency histograms "
        "(cyclonus_tpu_probe_latency_seconds) scrape here",
    )
    args = parser.parse_args(argv)
    if args.metrics_port is not None:
        from ..telemetry.server import start_metrics_server

        srv = start_metrics_server(args.metrics_port)
        print(f"telemetry: metrics on {srv.url}/metrics", file=sys.stderr)
    print(run_worker(args.jobs))
    return 0


if __name__ == "__main__":
    sys.exit(main())
