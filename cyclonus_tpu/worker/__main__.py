"""In-pod worker entrypoint (reference: cmd/worker/main.go + worker/cli.go):
`python -m cyclonus_tpu.worker --jobs '<batch json>'` issues the batch's
probes and prints JSON results on stdout (the driver-side Client parses
them from the kubectl-exec stream)."""

from __future__ import annotations

import argparse
import sys

from .worker import run_worker


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="cyclonus-worker", description="in-pod batch connectivity prober"
    )
    parser.add_argument(
        "--jobs", required=True, help="JSON-serialized worker Batch"
    )
    parser.add_argument(
        "--metrics-port",
        type=int,
        default=None,
        metavar="PORT",
        help="serve Prometheus /metrics on 127.0.0.1:PORT (0 = ephemeral) "
        "for the batch's duration — per-probe latency histograms "
        "(cyclonus_tpu_probe_latency_seconds) scrape here",
    )
    parser.add_argument(
        "--trace-out",
        default="",
        metavar="PATH",
        help="write this worker's own span timeline as Chrome trace "
        "JSON (standalone debugging; in a driver run the events ride "
        "back on the Results instead)",
    )
    args = parser.parse_args(argv)
    if args.trace_out:
        # standalone debugging: record this worker's own timeline even
        # without driver-supplied trace context on the batch
        from ..telemetry import events

        events.enable(role="worker")
    if args.metrics_port is not None:
        from ..telemetry.server import MetricsPortBusy, start_metrics_server

        try:
            srv = start_metrics_server(args.metrics_port)
        except MetricsPortBusy as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        print(
            f"telemetry: metrics on {srv.url}/metrics (port {srv.port})",
            file=sys.stderr,
        )
        # honest readiness: the worker is ready the moment it starts
        # consuming its batch (no warmup phase of its own)
        from ..telemetry.server import register_readiness

        register_readiness(lambda: (True, "worker processing batch"))
    print(run_worker(args.jobs))
    if args.trace_out:
        from ..telemetry import trace_export

        path = trace_export.write_chrome_trace(args.trace_out)
        print(f"trace: wrote {path}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
