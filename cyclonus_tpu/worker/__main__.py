"""In-pod worker entrypoint (reference: cmd/worker/main.go + worker/cli.go):
`python -m cyclonus_tpu.worker --jobs '<batch json>'` issues the batch's
probes and prints JSON results on stdout (the driver-side Client parses
them from the kubectl-exec stream)."""

from __future__ import annotations

import argparse
import sys

from .worker import run_worker


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="cyclonus-worker", description="in-pod batch connectivity prober"
    )
    parser.add_argument(
        "--jobs", required=True, help="JSON-serialized worker Batch"
    )
    args = parser.parse_args(argv)
    print(run_worker(args.jobs))
    return 0


if __name__ == "__main__":
    sys.exit(main())
