"""The wire protocol as a declarative, versioned registry — the static
twin tools/wirelint.py lints against and the peer version-skew harness
tests/skewharness.py replays against.

Every message crossing the worker/serve wire (Batch, Request, Result,
Delta, FlowQuery, Verdict, and the serve loop's Reply envelope) must
hold a five-way agreement: its emit sites write only declared keys
under their declared guards, its readers tolerate old peers (absent
optional keys) and new peers (unknown keys), its evolution stays
additive-optional against the frozen golden ``wire_schema.json``, its
replies stamp exactly one epoch, and its comparable fields stay
portable across peers.  Before this module that agreement lived in
hand-written ``WIRE`` ClassVar tables, compat comments in
worker/model.py's docstring, and per-key legacy-view test helpers; now
it is DECLARED here and everything derives from the declarations:

  * ``Key`` — one wire key: its JSON type, optionality, the protocol
    version that introduced it (``since``), its emit guard
    ("set" = only when set/truthy, "with=K" = only nested inside K's
    emit, "implies=K" = any payload carrying it also carries K), its
    float canonicalization (``canon``), whether its VALUE is
    comparable across peers (``portable`` — non-portable fields like
    latencies and trace events are stripped before replica/parity
    comparison), the nested registered message its items carry
    (``ref``), and a literal ``sample`` exemplar the skew harness
    synthesizes payloads from.
  * ``Message`` — one wire message: its introducing version and its
    epoch rule ("stamp" = every constructed instance carries an epoch;
    "from-verdicts" = the reply stamps exactly one epoch taken from
    its verdicts' own batch — wirelint WR004, the replica-read
    invariant ROADMAP item 1 stands on).
  * ``wire_table`` — derives the contracts.WireField dict the model
    classes validate against, so model.py's ``WIRE`` tables ARE the
    registry.
  * ``legacy_view`` / ``inject_unknown`` — synthesize what an older /
    newer peer would see, recursively through ``ref`` links; the skew
    harness and tests/test_worker.py's compat census both use these
    instead of hand-built per-key dicts.
  * ``build_golden`` — the frozen-schema projection committed as
    ``worker/wire_schema.json``; wirelint WR003 fails on any
    non-additive diff, and regenerating the golden
    (``python -m cyclonus_tpu.worker.wireregistry --write-golden``) is
    the explicit, diffable act of changing the protocol.

Protocol history (the version rows WR003 pins every key to):

  v1  frozen reference shape (Go-compatible): Batch base, Request,
      Result base.
  v2  Result.LatencyMs (per-probe wall-clock).
  v3  trace context: Batch.TraceId/ParentSpan, Result.TraceEvents.
  v4  the verdict service: Delta, FlowQuery, Verdict, Batch.Deltas/
      Queries, and the serve Reply envelope.
  v5  the SLO engine: Verdict.Shed, Reply.Admission.

Strip contract (same as serve/stateregistry.py): ``ACTIVE`` is read
ONCE at import.  When off — every production run — the skew-view call
recorder is a constant-false branch away from a no-op; armed
(CYCLONUS_SKEWHARNESS=1) it records which registry helpers synthesized
the views, so the harness can assert its skew coverage really is
registry-driven rather than a drifted hand-rolled copy.
"""

from __future__ import annotations

import copy
import json
import os
import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from ..utils import contracts

ACTIVE = os.environ.get("CYCLONUS_SKEWHARNESS", "") == "1"

#: the CURRENT protocol version — bump it (with a VERSIONS row) when a
#: key lands, then regenerate the golden
PROTOCOL_VERSION = 5

#: every version's row: wirelint WR003 rejects a key whose ``since``
#: has no row here ("a new key without a version row")
VERSIONS: Dict[int, str] = {
    1: "frozen reference shape (Batch/Request/Result base keys)",
    2: "Result.LatencyMs (per-probe wall-clock)",
    3: "trace context (Batch.TraceId/ParentSpan, Result.TraceEvents)",
    4: "verdict service (Delta/FlowQuery/Verdict, Batch.Deltas/Queries, Reply)",
    5: "SLO engine (Verdict.Shed, Reply.Admission)",
}


@dataclass(frozen=True)
class Key:
    name: str  # the wire key (Go-cased, matching the reference JSON)
    type: str  # JSON-level python type: str|int|float|bool|dict|list
    optional: bool = False  # absent-tolerated on parse, guarded on emit
    since: int = 1  # protocol version that introduced the key
    guard: str = ""  # "" derives: "always" (required) / "set" (optional)
    canon: str = ""  # declared float canonicalization (WR005)
    portable: bool = True  # value comparable across peers (WR005)
    ref: str = ""  # nested registered message carried by dict/list items
    sample: object = None  # literal exemplar for skew-view synthesis
    note: str = ""


@dataclass(frozen=True)
class Message:
    name: str
    since: int = 1  # protocol version that introduced the message
    epoch: str = ""  # "" | "stamp" | "from-verdicts" (wirelint WR004)
    keys: Tuple[Key, ...] = ()
    note: str = ""


_TYPES: Dict[str, type] = {
    "str": str, "int": int, "float": float,
    "bool": bool, "dict": dict, "list": list,
}

# --------------------------------------------------------------------------
# The message census.  Every row is a PURE LITERAL: tools/wirelint.py
# extracts this tuple off the AST without importing the package, and
# tests/test_wirelint.py pins that extraction byte-identical to
# manifest().
# --------------------------------------------------------------------------

MESSAGES: Tuple[Message, ...] = (
    Message(
        "Request", since=1,
        note="one probe ('can I connect') — model.go:26-48",
        keys=(
            Key("Key", "str", sample="probe-1"),
            Key("Protocol", "str", sample="TCP"),
            Key("Host", "str", sample="10.0.0.2"),
            Key("Port", "int", sample=80),
        ),
    ),
    Message(
        "Batch", since=1,
        note="the one envelope: probes to workers, deltas/queries to serve",
        keys=(
            Key("Namespace", "str", sample="x"),
            Key("Pod", "str", sample="a"),
            Key("Container", "str", sample="c"),
            Key("Requests", "list", ref="Request",
                sample=[{"Key": "probe-1", "Protocol": "TCP",
                         "Host": "10.0.0.2", "Port": 80}]),
            Key("TraceId", "str", optional=True, since=3, portable=False,
                sample="t-1",
                note="driver trace context; random per run, never compared"),
            Key("ParentSpan", "str", optional=True, since=3,
                guard="set,with=TraceId", portable=False, sample="0.1",
                note="rides only alongside TraceId (emit nesting, WR001)"),
            Key("Deltas", "list", optional=True, since=4, ref="Delta",
                sample=[{"Kind": "pod_add", "Namespace": "x", "Name": "a",
                         "Labels": {"app": "web"}, "Ip": "10.0.0.9"}]),
            Key("Queries", "list", optional=True, since=4, ref="FlowQuery",
                sample=[{"Src": "x/a", "Dst": "y/b", "Port": 80,
                         "Protocol": "TCP", "PortName": "http"}]),
        ),
    ),
    Message(
        "Result", since=1,
        note="one probe's answer — model.go:50-61",
        keys=(
            Key("Request", "dict", ref="Request",
                sample={"Key": "probe-1", "Protocol": "TCP",
                        "Host": "10.0.0.2", "Port": 80}),
            Key("Output", "str", sample="connected"),
            Key("Error", "str", sample=""),
            Key("LatencyMs", "float", optional=True, since=2,
                canon="round-ms", portable=False, sample=1.5,
                note="producer-rounded milliseconds (worker.py round(.,3))"),
            Key("TraceEvents", "list", optional=True, since=3,
                portable=False,
                sample=[{"name": "worker.probe", "pid": 7, "ts": 0.0}],
                note="carries pids/timestamps by design — never compared"),
        ),
    ),
    Message(
        "Delta", since=4,
        note="one cluster-state mutation; Kind selects the payload keys",
        keys=(
            Key("Kind", "str", since=4, sample="pod_add"),
            Key("Namespace", "str", since=4, sample="x"),
            Key("Name", "str", optional=True, since=4, sample="a"),
            Key("Labels", "dict", optional=True, since=4,
                sample={"app": "web"}),
            Key("Ip", "str", optional=True, since=4, sample="10.0.0.9"),
            Key("Policy", "dict", optional=True, since=4,
                sample={"metadata": {"name": "p", "namespace": "x"}},
                note="new kinds ride this SAME key — data, not new keys"),
        ),
    ),
    Message(
        "FlowQuery", since=4,
        note="one 'is this flow allowed' question",
        keys=(
            Key("Src", "str", since=4, sample="x/a"),
            Key("Dst", "str", since=4, sample="y/b"),
            Key("Port", "int", since=4, sample=80),
            Key("Protocol", "str", since=4, sample="TCP"),
            Key("PortName", "str", optional=True, since=4, sample="http"),
        ),
    ),
    Message(
        "Verdict", since=4, epoch="stamp",
        note="the service's answer; every instance stamps its epoch",
        keys=(
            Key("Query", "dict", since=4, ref="FlowQuery",
                sample={"Src": "x/a", "Dst": "y/b", "Port": 80,
                        "Protocol": "TCP", "PortName": "http"}),
            Key("Ingress", "bool", since=4, sample=True),
            Key("Egress", "bool", since=4, sample=True),
            Key("Combined", "bool", since=4, sample=True),
            Key("Epoch", "int", optional=True, since=4, sample=4,
                note="the staleness anchor for epoch-consistent reads"),
            Key("Error", "str", optional=True, since=4, sample="boom"),
            Key("LatencyMs", "float", optional=True, since=4,
                canon="round-ms", portable=False, sample=1.5),
            Key("Shed", "bool", optional=True, since=5,
                guard="set,implies=Error", sample=True,
                note="SLO refusal: only when True, always alongside Error"),
        ),
    ),
    Message(
        "Reply", since=4, epoch="from-verdicts",
        note="the serve loop's per-line answer envelope (serve/loop.py)",
        keys=(
            Key("Applied", "int", optional=True, since=4, sample=1),
            Key("Mode", "str", optional=True, since=4,
                sample="incremental"),
            Key("Epoch", "int", optional=True, since=4, sample=4,
                note="stamped on every non-error reply; exactly one, "
                     "taken from the verdicts' own batch (WR004)"),
            Key("Rejected", "list", optional=True, since=4,
                sample=[{"index": 0, "error": "bad kind"}]),
            Key("Verdicts", "list", optional=True, since=4, ref="Verdict",
                sample=[{"Query": {"Src": "x/a", "Dst": "y/b", "Port": 80,
                                   "Protocol": "TCP", "PortName": "http"},
                         "Ingress": True, "Egress": True, "Combined": True,
                         "Epoch": 4, "Error": "boom", "LatencyMs": 1.5,
                         "Shed": True}]),
            Key("Admission", "str", optional=True, since=5,
                sample="admission: freshness budget exhausted",
                note="SLO back-pressure: the batch was refused, retry"),
            Key("Error", "str", optional=True, since=4,
                sample="ValueError: malformed line",
                note="the malformed-line envelope (run_stdio)"),
        ),
    ),
)


# --------------------------------------------------------------------------
# Lookups and derived tables.
# --------------------------------------------------------------------------

def message(name: str) -> Message:
    for m in MESSAGES:
        if m.name == name:
            return m
    raise KeyError(f"unregistered wire message {name!r}")


def message_names() -> Tuple[str, ...]:
    return tuple(m.name for m in MESSAGES)


def effective_guard(k: Key) -> str:
    return k.guard or ("set" if k.optional else "always")


def wire_table(name: str) -> Dict[str, contracts.WireField]:
    """The contracts.WireField dict for one message — worker/model.py's
    ``WIRE`` ClassVars are these, so a key declared HERE is covered by
    check_wire / check_wire_read automatically."""
    return {
        k.name: contracts.wire(_TYPES[k.type], optional=k.optional)
        for k in message(name).keys
    }


def key_count() -> int:
    return sum(len(m.keys) for m in MESSAGES)


def _dependents(msg: Message, key_name: str) -> List[str]:
    """Keys whose guard ties them to `key_name` (ParentSpan with=TraceId):
    a view dropping the anchor must drop the dependents too, or the
    synthesized payload would violate its own declared guards."""
    out = []
    for k in msg.keys:
        for tok in (k.guard or "").split(","):
            if tok.strip() == f"with={key_name}":
                out.append(k.name)
                out.extend(_dependents(msg, k.name))
    return out


def _view(
    name: str,
    payload: dict,
    version: Optional[int],
    drop_unknown: bool,
    drop_keys: Tuple[str, ...] = (),
) -> dict:
    """The registry-driven skew projection: drop keys newer than
    `version` (None = current), optionally drop unknown keys (the
    old-reader simulation), always drop `drop_keys` plus their guard
    dependents — recursing through ``ref`` links so nested messages
    skew consistently (a v4 Reply view drops Shed from its Verdicts)."""
    msg = message(name)
    declared = {k.name: k for k in msg.keys}
    dropped = set(drop_keys)
    for d in drop_keys:
        dropped.update(_dependents(msg, d))
    out: dict = {}
    for key, value in payload.items():
        k = declared.get(key)
        if k is None:
            if drop_unknown:
                continue
            out[key] = copy.deepcopy(value)
            continue
        if key in dropped:
            continue
        if version is not None and k.since > version:
            continue
        if k.ref:
            if k.type == "list" and isinstance(value, list):
                value = [
                    _view(k.ref, v, version, drop_unknown)
                    if isinstance(v, dict) else copy.deepcopy(v)
                    for v in value
                ]
            elif k.type == "dict" and isinstance(value, dict):
                value = _view(k.ref, value, version, drop_unknown)
            else:
                value = copy.deepcopy(value)
        else:
            value = copy.deepcopy(value)
        out[key] = value
    return out


def legacy_view(name: str, payload: dict, version: int) -> dict:
    """What a version-`version` peer's payload looks like: every key
    introduced after `version` dropped, recursively.  This is the
    older-emitter->newer-reader synthesis (and equally, the key set an
    older READER would consider after ignoring unknowns)."""
    _record("legacy_view")
    return _view(name, payload, version, drop_unknown=False)


def drop_view(name: str, payload: dict, key: str) -> dict:
    """The per-key absence view: `key` (plus its guard dependents)
    removed — the 'this old peer never set it' case the per-key compat
    tests used to hand-build."""
    _record("drop")
    return _view(
        name, payload, None, drop_unknown=False, drop_keys=(key,)
    )


def inject_unknown(name: str, payload: dict) -> dict:
    """The newer-emitter view: an undeclared key injected at every
    level (top and inside each ``ref``), which every reader must
    ignore — the frozen tolerate-unknown-keys rule."""
    _record("inject")
    out = _view(name, payload, None, drop_unknown=False)
    out["XWireSkewProbe"] = {"from": "the-future"}
    msg = message(name)
    for k in msg.keys:
        if not k.ref or k.name not in out:
            continue
        v = out[k.name]
        if k.type == "list" and isinstance(v, list):
            out[k.name] = [
                dict(item, XWireSkewProbe=1)
                if isinstance(item, dict) else item
                for item in v
            ]
        elif k.type == "dict" and isinstance(v, dict):
            out[k.name] = dict(v, XWireSkewProbe=1)
    return out


def strip_nonportable(name: str, payload: dict) -> dict:
    """Drop every ``portable=False`` key, recursively — the
    registry-driven projection under which two peers' payloads for the
    same state must compare EQUAL (latencies, trace ids, and trace
    events are measurements, not state)."""
    msg = message(name)
    out: dict = {}
    for key, value in payload.items():
        k = next((x for x in msg.keys if x.name == key), None)
        if k is not None and not k.portable:
            continue
        if k is not None and k.ref:
            if k.type == "list" and isinstance(value, list):
                value = [
                    strip_nonportable(k.ref, v)
                    if isinstance(v, dict) else v
                    for v in value
                ]
            elif k.type == "dict" and isinstance(value, dict):
                value = strip_nonportable(k.ref, value)
        out[key] = value
    return out


def sample_payload(name: str) -> dict:
    """The fully-populated exemplar synthesized from the registry's
    literal ``sample`` column — every optional key present, so skew
    views exercise every declared key."""
    return {
        k.name: copy.deepcopy(k.sample)
        for k in message(name).keys
        if k.sample is not None
    }


def check_read(name: str, payload: object) -> None:
    """Reader-side validation against the registry table (the serve
    loop and the driver client call this under CYCLONUS_SHAPE_CHECK=1
    via contracts.check_wire_read)."""
    contracts.check_wire_read(name, payload, wire_table(name))


def guard_violations(name: str, payload: dict) -> List[str]:
    """Declared-guard conformance of one EMITTED payload: an
    ``implies=K`` key present without K, or a ``with=K`` key present
    without its anchor.  The skew harness asserts every live emit is
    clean; a violation names the key and the rule."""
    msg = message(name)
    out = []
    for k in msg.keys:
        if k.name not in payload:
            continue
        for tok in (k.guard or "").split(","):
            tok = tok.strip()
            for rule in ("implies=", "with="):
                if tok.startswith(rule) and tok[len(rule):] not in payload:
                    out.append(
                        f"{name}.{k.name}: declared '{tok}' but "
                        f"{tok.split('=', 1)[1]!r} absent from the payload"
                    )
    return out


# --------------------------------------------------------------------------
# The skew sweep: both peer directions for every registered message,
# synthesized from the registry.  tests/skewharness.py drives this
# (armed) plus the real serve wire loop; bench.py's detail.wire block
# stamps its counters on every BENCH line.
# --------------------------------------------------------------------------

def _generic_codec(name: str):
    """The registry-derived codec for messages with no model class (the
    Reply envelope): parse = validate + deep-restrict to declared keys
    (exactly what an old reader's ignore-unknowns parse yields), emit =
    identity."""

    def parse(d: dict) -> dict:
        check_read(name, d)
        return _view(name, d, None, drop_unknown=True)

    return parse, lambda obj: obj


def skew_sweep(
    codecs: Optional[Dict[str, Tuple[Callable, Callable]]] = None,
) -> Dict[str, object]:
    """For every registered message: the full-sample round-trip, every
    (older-emitter -> newer-reader) version view, every (newer-emitter
    -> older-reader) unknown-key injection, and every per-optional-key
    absence view — each driven through the real codec (worker/model.py
    CODECS) or the registry-generic one.  Returns the counters the
    census and detail.wire stamp, with any divergence in
    ``problems``."""
    codecs = codecs or {}
    pairs = 0
    problems: List[str] = []
    dropped_census: Dict[str, set] = {}
    present_census: Dict[str, set] = {}

    def note(msg_name: str, payload: dict, *, absent: Optional[set] = None):
        keys = {k.name for k in message(msg_name).keys if k.optional}
        present_census.setdefault(msg_name, set()).update(
            keys & set(payload)
        )
        if absent is not None:
            dropped_census.setdefault(msg_name, set()).update(
                absent & keys
            )

    for msg in MESSAGES:
        parse, emit = codecs.get(msg.name) or _generic_codec(msg.name)
        full = sample_payload(msg.name)

        def run_pair(view: dict, scenario: str) -> Optional[dict]:
            nonlocal pairs
            pairs += 1
            try:
                emitted = emit(parse(view))
            except Exception as e:  # noqa: BLE001 - reported, not raised
                problems.append(
                    f"{msg.name} {scenario}: parse/emit raised "
                    f"{type(e).__name__}: {e}"
                )
                return None
            if emitted != view:
                problems.append(
                    f"{msg.name} {scenario}: round-trip drifted "
                    f"(keys {sorted(view)} -> {sorted(emitted)})"
                )
            return emitted

        # full-sample round-trip + declared-guard conformance
        emitted = run_pair(full, "full")
        note(msg.name, full)
        if emitted is not None:
            problems.extend(guard_violations(msg.name, emitted))
        # older emitter -> newer reader, at every prior version
        for v in range(msg.since, PROTOCOL_VERSION):
            view = legacy_view(msg.name, full, v)
            run_pair(view, f"older-emitter(v{v})")
            note(msg.name, view, absent=set(full) - set(view))
            problems.extend(guard_violations(msg.name, view))
            # newer emitter -> older reader: unknown keys injected on
            # top of the same view must parse identically
            pairs += 1
            try:
                a = emit(parse(inject_unknown(msg.name, view)))
                b = emit(parse(view))
            except Exception as e:  # noqa: BLE001
                problems.append(
                    f"{msg.name} newer-emitter(v{v}): unknown key broke "
                    f"the parse: {type(e).__name__}: {e}"
                )
            else:
                if a != b:
                    problems.append(
                        f"{msg.name} newer-emitter(v{v}): unknown keys "
                        f"leaked into the parse ({sorted(b)} -> "
                        f"{sorted(a)})"
                    )
        # per-optional-key absence (the old peer never set it)
        for k in msg.keys:
            if not k.optional:
                continue
            view = drop_view(msg.name, full, k.name)
            run_pair(view, f"absent({k.name})")
            note(msg.name, view, absent=set(full) - set(view))
    return {
        "schema_version": PROTOCOL_VERSION,
        "messages": len(MESSAGES),
        "keys": key_count(),
        "skew_pairs_checked": pairs,
        "problems": problems,
        "census": {
            "dropped": {m: sorted(s) for m, s in dropped_census.items()},
            "present": {m: sorted(s) for m, s in present_census.items()},
        },
    }


def census_gaps(sweep: Dict[str, object]) -> List[str]:
    """Registered optional keys the sweep never exercised under skew —
    both directions required: present in a parsed view AND absent from
    one.  The coverage census `make skewharness` fails on."""
    census = sweep.get("census") or {}
    dropped = census.get("dropped") or {}
    present = census.get("present") or {}
    gaps = []
    for m in MESSAGES:
        for k in m.keys:
            if not k.optional:
                continue
            if k.name not in (present.get(m.name) or ()):
                gaps.append(f"{m.name}.{k.name}: never present under skew")
            if k.name not in (dropped.get(m.name) or ()):
                gaps.append(f"{m.name}.{k.name}: never absent under skew")
    return gaps


# --------------------------------------------------------------------------
# The frozen golden (worker/wire_schema.json) and the manifest.
# --------------------------------------------------------------------------

def build_golden() -> Dict[str, object]:
    """The evolution-relevant projection of the registry: type,
    optionality, and version row per key.  Committed as
    wire_schema.json; wirelint WR003 diffs the live registry against
    it, so ANY protocol change is a golden regeneration — a reviewable
    diff — and additive-optional is the only change that passes."""
    return {
        "schema_version": PROTOCOL_VERSION,
        "versions": {str(v): note for v, note in sorted(VERSIONS.items())},
        "messages": {
            m.name: {
                "since": m.since,
                "epoch": m.epoch,
                "keys": {
                    k.name: {
                        "type": k.type,
                        "optional": k.optional,
                        "since": k.since,
                    }
                    for k in m.keys
                },
            }
            for m in MESSAGES
        },
    }


def golden_path() -> str:
    return os.path.join(os.path.dirname(__file__), "wire_schema.json")


def manifest() -> Dict[str, object]:
    """The full registry as plain JSON-able data.
    tests/test_wirelint.py pins tools/wirelint.py's AST extraction
    byte-identical to this — the proof the static twin lints the REAL
    declarations."""
    return {
        "version": 1,
        "protocol_version": PROTOCOL_VERSION,
        "versions": {str(v): note for v, note in sorted(VERSIONS.items())},
        "messages": [
            {
                "name": m.name,
                "since": m.since,
                "epoch": m.epoch,
                "note": m.note,
                "keys": [
                    {
                        "name": k.name,
                        "type": k.type,
                        "optional": k.optional,
                        "since": k.since,
                        "guard": effective_guard(k),
                        "canon": k.canon,
                        "portable": k.portable,
                        "ref": k.ref,
                        "sample": k.sample,
                        "note": k.note,
                    }
                    for k in m.keys
                ],
            }
            for m in MESSAGES
        ],
    }


# --------------------------------------------------------------------------
# The harness-mode call recorder (strip contract: ACTIVE read once at
# import; disarmed, _record is a constant-false branch away from free).
# --------------------------------------------------------------------------

_CALLS_LOCK = threading.Lock()
_CALLS: List[str] = []  # guarded-by: _CALLS_LOCK


def _record(op: str) -> None:  # never-raises
    if not ACTIVE:
        return
    with _CALLS_LOCK:
        _CALLS.append(op)


def drain() -> List[str]:
    """The skew-view helper calls recorded since the last drain (armed
    mode only; disarmed, always empty)."""
    if not ACTIVE:
        return []
    with _CALLS_LOCK:
        out = list(_CALLS)
        _CALLS.clear()
        return out


def _main(argv: Optional[List[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="wire registry tools (see module docstring)"
    )
    ap.add_argument(
        "--write-golden", action="store_true",
        help="regenerate worker/wire_schema.json from the registry — "
             "the explicit act of changing the wire protocol",
    )
    args = ap.parse_args(argv)
    if args.write_golden:
        path = golden_path()
        with open(path, "w") as f:
            json.dump(build_golden(), f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"wrote {path}")
        return 0
    print(json.dumps(manifest(), indent=2, sort_keys=True))
    return 0


if __name__ == "__main__":
    raise SystemExit(_main())
