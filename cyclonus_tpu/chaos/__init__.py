"""Chaos layer: deterministic fault injection for the cold-start /
crash-survival contract (docs/DESIGN.md "Cold start & chaos").

Two halves:

  - injection hooks (this module): named points compiled into the
    production code paths — `fire(point)` raises ChaosError and
    `stall(point)` sleeps — armed ONLY via CYCLONUS_CHAOS, so the
    hooks are two dict reads when disarmed.  Points today:

        backend_init       bench.py's overlapped attach thread
        delta_apply        VerdictService.apply_pending, AFTER the
                           authoritative dicts mutated (exercises the
                           rollback + rebuild-to-snapshot path)
        worker_wire        worker/client.py batch issue (raise)
        worker_wire_stall  worker/client.py batch issue (sleep ARG
                           seconds; trips the per-batch timeout)
        verdict_corrupt    audit/sampler.py offer(): flips a SAMPLED
                           verdict's allow bits at the audit intake —
                           the end-to-end proof the shadow-oracle
                           sampler detects a corruption within a
                           bounded number of checks

  - the harness (chaos/harness.py): seeded, bounded scenarios — kill
    and restart `cyclonus-tpu serve` mid-churn with a bounded
    time-to-first-verdict, poison/truncate the AOT + autotune caches,
    fail backend init N times, stall the worker wire, drop a delta
    batch mid-apply — each asserting the system degrades exactly as
    designed (fresh compile / retry / rollback; incremental == rebuild
    == oracle parity after every injected fault).  `make chaos` runs
    them all; bench.py's detail.chaos leg runs the kill/restart one.

Spec grammar (CYCLONUS_CHAOS): comma-separated `point[:count[:arg]]` —
`count` faults fire at that point then the hook disarms (default 1);
`arg` is the point-specific float (stall seconds).  Example:

    CYCLONUS_CHAOS="backend_init:2,worker_wire_stall:1:0.5"

Every fired fault counts into cyclonus_tpu_chaos_injections_total by
point, so a chaos run's artifact shows exactly what was injected.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional

__all__ = [
    "ChaosError",
    "armed",
    "disarm",
    "fire",
    "injected",
    "reset",
    "stall",
]


class ChaosError(RuntimeError):
    """An injected fault (never raised unless CYCLONUS_CHAOS armed it)."""

    def __init__(self, point: str):
        super().__init__(f"chaos: injected fault at {point!r}")
        self.point = point


_LOCK = threading.Lock()
# {"env": spec string the budgets were parsed from, "budgets":
#  {point: [remaining, arg]}, "fired": {point: count}, "gen":
#  arm-generation counter (see disarm)}
_STATE: Dict = {"env": None, "budgets": {}, "fired": {}, "gen": 0}  # guarded-by: _LOCK


def _parse(spec: str) -> Dict[str, list]:
    budgets: Dict[str, list] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        bits = part.split(":")
        point = bits[0]
        try:
            count = int(bits[1]) if len(bits) > 1 else 1
        except ValueError:
            count = 1
        try:
            arg = float(bits[2]) if len(bits) > 2 else None
        except ValueError:
            arg = None
        budgets[point] = [max(0, count), arg]
    return budgets


def reset(spec: Optional[str] = None) -> int:
    """Re-arm from `spec` (tests/harness), or from the CURRENT env when
    None.  Clears fired counts.  An explicit spec is written back to
    CYCLONUS_CHAOS — the hooks re-sync from the env, so the two must
    agree or the next hook would silently re-parse the stale env.
    Returns an arm-generation token for `disarm` — a scenario thread
    abandoned past its bound must not clear the budget a LATER
    scenario armed."""
    if spec is None:
        spec = os.environ.get("CYCLONUS_CHAOS", "")
    else:
        os.environ["CYCLONUS_CHAOS"] = spec
    with _LOCK:
        _STATE["env"] = spec
        _STATE["budgets"] = _parse(spec)
        _STATE["fired"] = {}
        _STATE["gen"] += 1
        return _STATE["gen"]


def disarm(token: Optional[int] = None) -> None:
    """Clear the armed spec — but ONLY if `token` is still the current
    arm generation (None forces).  The token-checked form is what
    scenario `finally` blocks use: if the scenario was abandoned by
    run_bounded and a later scenario has re-armed, the stale thread's
    cleanup becomes a no-op instead of disarming mid-scenario."""
    with _LOCK:
        if token is not None and token != _STATE["gen"]:
            return
        os.environ["CYCLONUS_CHAOS"] = ""
        _STATE["env"] = ""
        _STATE["budgets"] = {}
        _STATE["fired"] = {}
        _STATE["gen"] += 1


def _budget(point: str):
    """The live [remaining, arg] for `point`, re-parsing when the env
    changed since the last look (subprocess harnesses set the env
    before import, long-lived tests flip it between scenarios)."""
    env = os.environ.get("CYCLONUS_CHAOS", "")
    with _LOCK:
        if env != _STATE["env"]:
            _STATE["env"] = env
            _STATE["budgets"] = _parse(env)
            _STATE["fired"] = {}
        return _STATE["budgets"].get(point)


def armed(point: str) -> bool:
    b = _budget(point)
    return bool(b and b[0] > 0)


def _consume(point: str):
    """Decrement the budget under the lock; returns the arg when a
    fault should fire, else None-sentinel False."""
    env = os.environ.get("CYCLONUS_CHAOS", "")
    with _LOCK:
        if env != _STATE["env"]:
            _STATE["env"] = env
            _STATE["budgets"] = _parse(env)
            _STATE["fired"] = {}
        b = _STATE["budgets"].get(point)
        if not b or b[0] <= 0:
            return False
        b[0] -= 1
        _STATE["fired"][point] = _STATE["fired"].get(point, 0) + 1
        arg = b[1]
    _count(point)
    return (arg,)


def fire(point: str) -> None:
    """Raise ChaosError at `point` while its budget lasts; no-op
    otherwise.  The production call sites sit on paths that already
    survive real faults of the same class — the raise must flow
    through the SAME retry/rollback machinery a real failure would."""
    if _consume(point) is not False:
        raise ChaosError(point)


def stall(point: str, default_s: float = 1.0) -> float:
    """Sleep the point's arg (or `default_s`) while its budget lasts;
    returns the seconds slept (0.0 when disarmed).  The sleep happens
    OUTSIDE the state lock."""
    hit = _consume(point)
    if hit is False:
        return 0.0
    seconds = hit[0] if hit[0] is not None else default_s
    time.sleep(max(0.0, float(seconds)))
    return float(seconds)


def injected() -> Dict[str, int]:
    """Faults fired so far, by point (this process)."""
    with _LOCK:
        return dict(_STATE["fired"])


def _count(point: str) -> None:
    try:
        from ..telemetry import instruments as ti

        ti.CHAOS_INJECTIONS.inc(point=point)
    except Exception:
        pass  # chaos must degrade to a no-op if telemetry is absent
