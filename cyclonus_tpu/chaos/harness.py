"""The chaos suite: seeded, bounded fault-injection scenarios
(docs/DESIGN.md "Cold start & chaos"; `make chaos` runs them all,
`cyclonus-tpu chaos` is the CLI).

Each scenario injects ONE fault class and asserts the designed
degradation — retry, rollback, fresh compile, bounded restart — plus
the differential invariant that matters after the fault: verdicts stay
oracle-exact.  Scenarios are pure functions returning a report dict
with an "ok" flag; run_all wraps each in the bounded-run discipline so
a wedged scenario costs its bound, never the suite.
"""

from __future__ import annotations

import json
import os
import random
import subprocess
import sys
import time
from typing import Dict, List, Optional

from . import ChaosError, disarm, injected, reset

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: default wall-clock bound on a restarted replica's time-to-first-
#: verdict (CYCLONUS_CHAOS_TTFV_S overrides; generous because a CPU CI
#: restart pays the full jax import, not just the engine build)
DEFAULT_TTFV_BOUND_S = 150.0


def _ttfv_bound_s() -> float:
    try:
        return float(os.environ.get("CYCLONUS_CHAOS_TTFV_S", str(DEFAULT_TTFV_BOUND_S)))
    except ValueError:
        return DEFAULT_TTFV_BOUND_S


class _Serve:
    """A real `cyclonus-tpu serve` subprocess on the JSON-lines wire
    (stderr to a file so a chatty child can never deadlock the pipe)."""

    def __init__(self, n_pods: int, n_ns: int, seed: int, workdir: str,
                 tag: str, env: Optional[Dict[str, str]] = None):
        self.stderr_path = os.path.join(workdir, f"serve-{tag}.stderr")
        # children INHERIT the caller's backend: `make chaos` and the
        # test suite export JAX_PLATFORMS=cpu themselves, while the
        # bench's TPU-only chaos leg exists precisely to measure a TPU
        # replica's restart (a forced-CPU child would record a CPU
        # ttfv and could not adopt the TPU AOT entries — platform
        # stamp mismatch)
        full_env = dict(os.environ)
        full_env.update(env or {})
        self._stderr = open(self.stderr_path, "w")
        self.started_at = time.perf_counter()
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "cyclonus_tpu", "serve",
             "--synthetic-pods", str(n_pods),
             "--synthetic-namespaces", str(n_ns),
             "--seed", str(seed)],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=self._stderr, text=True, bufsize=1,
            env=full_env, cwd=REPO,
        )

    def round_trip(self, line: str) -> dict:
        self.proc.stdin.write(line + "\n")
        self.proc.stdin.flush()
        reply = self.proc.stdout.readline()
        if not reply:
            raise RuntimeError(
                f"serve died mid-reply (rc={self.proc.poll()}); stderr "
                f"tail: {open(self.stderr_path).read()[-500:]}"
            )
        return json.loads(reply)

    def kill(self) -> None:
        self.proc.kill()
        self.proc.wait(timeout=30)
        self._stderr.close()

    def close(self) -> int:
        try:
            self.proc.stdin.close()
        except OSError:
            pass
        rc = self.proc.wait(timeout=60)
        self._stderr.close()
        return rc


def _oracle_check(pods_state, namespaces, netpols, queries, verdicts) -> int:
    """Every wire verdict must equal the scalar oracle over the SAME
    post-delta state the harness mirrored — the restarted replica is a
    rebuild, so this IS the incremental==rebuild==oracle parity leg."""
    from ..analysis.oracle import oracle_verdicts, traffic_for_cell
    from ..engine.api import PortCase
    from ..matcher.builder import build_network_policies

    policy = build_network_policies(True, list(netpols))
    plist = list(pods_state.values())
    idx = {f"{p[0]}/{p[1]}": i for i, p in enumerate(plist)}
    checked = 0
    for q, v in zip(queries, verdicts):
        if v.get("Error"):
            raise AssertionError(f"query errored after fault: {v}")
        case = PortCase(q.port, q.port_name, q.protocol)
        want = oracle_verdicts(
            policy,
            traffic_for_cell(plist, namespaces, case, idx[q.src], idx[q.dst]),
        )
        got = (v["Ingress"], v["Egress"], v["Combined"])
        if got != want:
            raise AssertionError(
                f"CHAOS PARITY: {q.src}->{q.dst}: service={got} "
                f"oracle={want}"
            )
        checked += 1
    return checked


def scenario_serve_kill_restart(
    seed: int = 0,
    workdir: Optional[str] = None,
    n_pods: int = 24,
    churn_steps: int = 6,
    ttfv_bound_s: Optional[float] = None,
) -> Dict:
    """SIGKILL a serve replica mid-churn, restart it against the same
    (persistent) caches, and bound its time-to-first-verdict; verdicts
    after the restart — including after a fresh delta batch — must be
    oracle-exact."""
    import tempfile

    from ..cli.serve_cmd import synthetic_cluster
    from ..worker.model import Batch, Delta, FlowQuery

    bound = ttfv_bound_s if ttfv_bound_s is not None else _ttfv_bound_s()
    workdir = workdir or tempfile.mkdtemp(prefix="cyclonus-chaos-")
    n_ns = 3
    rng = random.Random(seed)
    pods, namespaces = synthetic_cluster(n_pods, n_ns, seed)
    state = {f"{p[0]}/{p[1]}": p for p in pods}
    keys = list(state)

    def churn_line(step: int) -> tuple:
        key = keys[rng.randrange(len(keys))]
        ns, name = key.split("/", 1)
        labels = {"pod": f"p{step}", "app": f"app{rng.randrange(20)}",
                  "tier": f"tier{rng.randrange(5)}"}
        return key, labels, Batch(
            namespace="", pod="", container="",
            deltas=[Delta(kind="pod_labels", namespace=ns, name=name,
                          labels=dict(labels))],
        ).to_json()

    # phase 1: a replica under churn, killed without warning mid-stream
    srv = _Serve(n_pods, n_ns, seed, workdir, "victim")
    applied_before_kill = 0
    for step in range(churn_steps):
        _key, _labels, line = churn_line(step)
        reply = srv.round_trip(line)
        if reply.get("Error"):
            raise AssertionError(f"churn delta rejected: {reply}")
        applied_before_kill += 1
    srv.kill()  # mid-churn: no shutdown, no flush — the crash case

    # phase 2: the restarted replica rebuilds from its source of truth
    # (the deltas above died with the victim — by design: authoritative
    # state is upstream, the replica is a cache of it), adopting the
    # persistent AOT/autotune caches.  TTFV = process start -> first
    # verdict reply on the wire, prewarm included.
    rng2 = random.Random(seed + 1)
    queries = [
        FlowQuery(src=rng2.choice(keys), dst=rng2.choice(keys), port=80,
                  protocol="TCP", port_name="serve-80-tcp")
        for _ in range(8)
    ]
    srv2 = _Serve(n_pods, n_ns, seed, workdir, "restarted")
    reply = srv2.round_trip(Batch(
        namespace="", pod="", container="", queries=queries,
    ).to_json())
    ttfv_s = time.perf_counter() - srv2.started_at
    checked = _oracle_check(
        {f"{p[0]}/{p[1]}": p for p in pods}, namespaces, [],
        queries, reply.get("Verdicts") or [],
    )
    # post-restart churn: the incremental path must survive the fault
    key, labels, line = churn_line(999)
    delta_reply = srv2.round_trip(line)
    if delta_reply.get("Mode") not in ("incremental", "class_rebuild"):
        raise AssertionError(
            f"post-restart delta fell off the incremental path: "
            f"{delta_reply}"
        )
    p = state[key]
    post_state = dict({f"{q[0]}/{q[1]}": q for q in pods})
    post_state[key] = (p[0], p[1], labels, p[3])
    reply2 = srv2.round_trip(Batch(
        namespace="", pod="", container="", queries=queries,
    ).to_json())
    checked += _oracle_check(
        post_state, namespaces, [], queries, reply2.get("Verdicts") or []
    )
    rc = srv2.close()
    if rc != 0:
        raise AssertionError(f"restarted serve exited rc={rc}")
    if ttfv_s > bound:
        raise AssertionError(
            f"time-to-first-verdict {ttfv_s:.1f}s exceeds the "
            f"{bound:g}s bound (CYCLONUS_CHAOS_TTFV_S)"
        )
    return {
        "ok": True,
        "applied_before_kill": applied_before_kill,
        "ttfv_s": round(ttfv_s, 3),
        "ttfv_bound_s": bound,
        "oracle_checked": checked,
    }


def _poison_file(path: str, mode: str) -> None:
    if mode == "truncate":
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            head = f.read(max(1, size // 2))
        with open(path, "wb") as f:
            f.write(head)
    elif mode == "garbage":
        with open(path, "wb") as f:
            f.write(b"\x00not a pickle\xff" * 64)
    elif mode == "version_skew":
        import pickle

        with open(path, "wb") as f:
            pickle.dump({"v": 9999, "key": "?", "payload": b""}, f)
    else:
        raise ValueError(mode)


def scenario_poisoned_caches(
    seed: int = 0, workdir: Optional[str] = None, n_pods: int = 24
) -> Dict:
    """Poison/truncate/version-skew every persisted cache — AOT
    executables AND the autotune winners — then build a fresh engine:
    it must degrade to fresh compiles (never raise) and stay
    bit-identical to the pre-poison engine."""
    import tempfile

    import numpy as np

    workdir = workdir or tempfile.mkdtemp(prefix="cyclonus-chaos-")
    aot_dir = os.path.join(workdir, "aot")
    tune_path = os.path.join(workdir, "autotune.json")
    saved = {
        k: os.environ.get(k)
        for k in ("CYCLONUS_AOT_CACHE", "CYCLONUS_AUTOTUNE_CACHE")
    }
    os.environ["CYCLONUS_AOT_CACHE"] = aot_dir
    os.environ["CYCLONUS_AUTOTUNE_CACHE"] = tune_path
    try:
        from ..cli.serve_cmd import synthetic_cluster
        from ..engine import PortCase, TpuPolicyEngine
        from ..engine import aot_cache
        from ..matcher.builder import build_network_policies
        from ..telemetry import instruments as ti

        pods, namespaces = synthetic_cluster(n_pods, 3, seed)
        policy = build_network_policies(True, [])
        cases = [PortCase(80, "chaos-80-tcp", "TCP")]
        eng_a = TpuPolicyEngine(policy, pods, namespaces)
        grid_a = np.asarray(eng_a.evaluate_grid(cases).combined)
        pairs_a = eng_a.evaluate_pairs(cases, [(0, 1), (1, 0)])
        entries = sorted(
            os.path.join(aot_dir, f)
            for f in os.listdir(aot_dir)
            if f.endswith(".aotx")
        )
        if not entries:
            raise AssertionError("no AOT entries written to poison")
        modes = ["truncate", "garbage", "version_skew"]
        for i, path in enumerate(entries):
            _poison_file(path, modes[i % len(modes)])
        with open(tune_path, "w") as f:
            f.write('{"v": 1, "entries": {truncated')
        corrupt0 = ti.AOT_CACHE.value(outcome="corrupt") + ti.AOT_CACHE.value(
            outcome="stale"
        )
        eng_b = TpuPolicyEngine(policy, pods, namespaces)
        grid_b = np.asarray(eng_b.evaluate_grid(cases).combined)
        pairs_b = eng_b.evaluate_pairs(cases, [(0, 1), (1, 0)])
        if not np.array_equal(grid_a, grid_b):
            raise AssertionError("grid diverged after cache poisoning")
        if not np.array_equal(pairs_a, pairs_b):
            raise AssertionError("pairs diverged after cache poisoning")
        rejected = (
            ti.AOT_CACHE.value(outcome="corrupt")
            + ti.AOT_CACHE.value(outcome="stale")
            - corrupt0
        )
        if rejected <= 0:
            raise AssertionError(
                "poisoned AOT entries were not detected (no corrupt/"
                "stale outcomes counted)"
            )
        return {
            "ok": True,
            "entries_poisoned": len(entries),
            "rejected": int(rejected),
            "aot": aot_cache.counters(),
        }
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def scenario_backend_init_flake(seed: int = 0, failures: int = 2) -> Dict:
    """Arm the `backend_init` point for N failures and drive the
    bench-shaped retry envelope (same jittered backoff helper): the
    attach must recover on attempt N+1 with the structured last-error
    retained — the exact forensics bench.py ships in
    detail.cold_start."""
    from ..utils.retry import full_jitter_pause
    from . import fire

    tok = reset(f"backend_init:{failures}")
    try:
        rng = random.Random(seed)
        state: Dict = {"attempts": 0, "last_error": None}
        recovered_at = None
        for attempt in range(1, failures + 2):
            state["attempts"] = attempt
            try:
                fire("backend_init")
                recovered_at = attempt
                break
            except ChaosError as e:
                state["last_error"] = {
                    "type": type(e).__name__,
                    "message": str(e)[:200],
                }
            time.sleep(min(0.05, full_jitter_pause(0.01, attempt, rng)))
        if recovered_at != failures + 1:
            raise AssertionError(
                f"retry loop recovered at attempt {recovered_at}, "
                f"expected {failures + 1}"
            )
        if (state["last_error"] or {}).get("type") != "ChaosError":
            raise AssertionError(
                f"structured last_error missing: {state['last_error']}"
            )
        return {
            "ok": True,
            "attempts": state["attempts"],
            "last_error": state["last_error"],
            "injected": injected(),
        }
    finally:
        disarm(tok)


class _InProcessKube:
    """The minimal IKubernetes a worker Client needs: run the in-pod
    worker in-process (same JSON contract as kubectl exec)."""

    def execute_remote_command(self, namespace, pod, container, command):
        from ..worker.worker import run_worker

        return run_worker(command[2]), "", None


def scenario_worker_wire(seed: int = 0, failures: int = 2) -> Dict:
    """Kill the worker wire N times mid-batch: the driver-side client
    must retry with backoff (cyclonus_tpu_worker_retries_total moves)
    and the batch must complete — a dead worker wedges nothing."""
    from ..telemetry import instruments as ti
    from ..worker.client import Client
    from ..worker.model import Batch

    tok = reset(f"worker_wire:{failures}")
    saved = {
        k: os.environ.get(k)
        for k in ("CYCLONUS_WORKER_BACKOFF_S", "CYCLONUS_WORKER_TIMEOUT_S")
    }
    os.environ["CYCLONUS_WORKER_BACKOFF_S"] = "0.01"
    try:
        retries0 = ti.WORKER_RETRIES.value()
        client = Client(_InProcessKube())
        results = client.batch(
            Batch(namespace="x", pod="a", container="c", requests=[])
        )
        retried = int(ti.WORKER_RETRIES.value() - retries0)
        if retried != failures:
            raise AssertionError(
                f"expected {failures} retries, counted {retried}"
            )
        return {
            "ok": True,
            "retries": retried,
            "results": len(results),
            "injected": injected(),
        }
    finally:
        disarm(tok)
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def scenario_delta_drop(seed: int = 0, n_pods: int = 16) -> Dict:
    """Drop a delta batch mid-apply (after the authoritative dicts
    mutated): the service must roll the batch back wholesale, stay
    incremental==rebuild==oracle consistent, and accept the next batch
    cleanly."""
    from ..cli.serve_cmd import synthetic_cluster
    from ..serve import VerdictService
    from ..worker.model import Delta

    pods, namespaces = synthetic_cluster(n_pods, 2, seed)
    svc = VerdictService(pods, namespaces, [])
    epoch0 = svc.epoch
    key = next(iter(svc.pods))
    ns, name = key.split("/", 1)
    delta = Delta(kind="pod_labels", namespace=ns, name=name,
                  labels={"app": "chaos", "pod": "p0", "tier": "t0"})
    tok = reset("delta_apply:1")
    try:
        raised = False
        try:
            svc.apply([delta])
        except ChaosError:
            raised = True
        if not raised:
            raise AssertionError("injected delta_apply fault did not fire")
        if svc.epoch != epoch0:
            raise AssertionError("epoch advanced through a dropped batch")
        if svc.pods[key][2].get("app") == "chaos":
            raise AssertionError("rollback left the mutated pod labels")
        parity1 = svc.verify_parity(oracle_samples=8)
        report = svc.apply([delta])
        if report["epoch"] != epoch0 + 1:
            raise AssertionError(f"post-fault apply failed: {report}")
        parity2 = svc.verify_parity(oracle_samples=8)
        return {
            "ok": True,
            "rolled_back": True,
            "parity": [parity1, parity2],
            "injected": injected(),
        }
    finally:
        disarm(tok)


def scenario_slo_ttfv(
    seed: int = 0,
    workdir: Optional[str] = None,
) -> Dict:
    """The SLO leg, both halves of the ttfv objective's contract:

    (a) kill/restart mid-churn must stay inside the DECLARED
        time-to-first-verdict error budget — CYCLONUS_SLO_TTFV_S, the
        same target the in-service controller enforces, not the looser
        harness bound — so the chaos suite and the SLO engine cannot
        drift apart on what a tolerable restart is;
    (b) the breach path: an over-budget first verdict (forced with a
        tiny target) must dump the flight recorder with the triggering
        objective in its reason, because a breach nobody can diagnose
        afterwards is just an outage with a counter."""
    import dataclasses
    import tempfile

    from ..slo.engine import SloController
    from ..slo.objectives import declared_objectives
    from ..utils import envflags

    workdir = workdir or tempfile.mkdtemp(prefix="cyclonus-chaos-slo-")

    # (a) restart bounded by the declared objective (smaller cluster
    # than serve_kill_restart: this leg asserts the budget, not churn
    # breadth, and the suite pays both scenarios)
    ttfv_target = envflags.get_float("CYCLONUS_SLO_TTFV_S")
    restart = scenario_serve_kill_restart(
        seed=seed, workdir=workdir, n_pods=12, churn_steps=3,
        ttfv_bound_s=ttfv_target,
    )

    # (b) forced breach -> black-box dump naming the objective
    dump_file = os.path.join(workdir, "slo-breach.json")
    ttfv_obj = next(o for o in declared_objectives() if o.name == "ttfv")
    ctl = SloController(
        [dataclasses.replace(ttfv_obj, target_s=0.001)], enforce=True
    )
    prev = os.environ.get("CYCLONUS_FLIGHT_RECORDER_PATH")
    os.environ["CYCLONUS_FLIGHT_RECORDER_PATH"] = dump_file
    try:
        ctl.observe_ttfv(5.0)  # 5s against a 1ms target: exhaustion
    finally:
        if prev is None:
            os.environ.pop("CYCLONUS_FLIGHT_RECORDER_PATH", None)
        else:
            os.environ["CYCLONUS_FLIGHT_RECORDER_PATH"] = prev
    if ctl.state_of("ttfv") != "exhausted":
        raise AssertionError(
            f"over-budget ttfv left state {ctl.state_of('ttfv')!r}, "
            "expected 'exhausted'"
        )
    if not os.path.exists(dump_file):
        raise AssertionError("slo breach produced no flight-recorder dump")
    with open(dump_file) as f:
        dumped = json.load(f)
    if dumped.get("reason") != "slo-breach:ttfv":
        raise AssertionError(
            f"breach dump reason {dumped.get('reason')!r} does not name "
            "the objective (want 'slo-breach:ttfv')"
        )
    breach_entries = [
        e for e in dumped.get("entries") or []
        if e.get("path") == "slo.breach"
    ]
    if not breach_entries:
        raise AssertionError("breach dump carries no slo.breach entry")
    return {
        "ok": True,
        "restart": restart,
        "ttfv_budget_s": ttfv_target,
        "breach_dump": dump_file,
        "breach_reason": dumped["reason"],
    }


def scenario_audit_divergence(
    seed: int = 0,
    workdir: Optional[str] = None,
    n_pods: int = 12,
    check_budget: int = 32,
) -> Dict:
    """The audit plane's end-to-end detection contract, on a REAL serve
    under churn: arm `verdict_corrupt` (one flipped sampled verdict)
    and the shadow-oracle sampler must detect it within the check
    budget, leaving an `audit-divergence` flight-recorder bundle on
    disk; then the SAME churn with the point disarmed must finish with
    no divergence dump at all."""
    import tempfile

    from ..worker.model import Batch, Delta, FlowQuery

    workdir = workdir or tempfile.mkdtemp(prefix="cyclonus-chaos-audit-")
    n_ns = 2
    rng = random.Random(seed)

    def churn(srv, keys, dump_file, budget) -> Optional[int]:
        """Deltas + query batches until the divergence dump appears (the
        audit worker is async — poll between batches); returns the
        number of audited-eligible queries sent before detection, or
        None when the budget ran out without a dump."""
        sent = 0
        for step in range(budget):
            key = keys[rng.randrange(len(keys))]
            ns, name = key.split("/", 1)
            line = Batch(
                namespace="", pod="", container="",
                deltas=[Delta(
                    kind="pod_labels", namespace=ns, name=name,
                    labels={"pod": f"p{step}", "app": f"a{step % 7}"},
                )],
                queries=[FlowQuery(
                    src=keys[rng.randrange(len(keys))],
                    dst=keys[rng.randrange(len(keys))],
                    port=80, protocol="TCP", port_name="serve-80-tcp",
                )],
            ).to_json()
            reply = srv.round_trip(line)
            if reply.get("Error"):
                raise AssertionError(f"churn line rejected: {reply}")
            sent += 1
            deadline = time.perf_counter() + 0.5
            while time.perf_counter() < deadline:
                if os.path.exists(dump_file):
                    return sent
                time.sleep(0.05)
        return None

    from ..cli.serve_cmd import synthetic_cluster

    pods, _namespaces = synthetic_cluster(n_pods, n_ns, seed)
    keys = [f"{p[0]}/{p[1]}" for p in pods]

    # phase 1: armed — every query sampled (rate 1.0), one corruption
    armed_dump = os.path.join(workdir, "audit-armed.json")
    srv = _Serve(n_pods, n_ns, seed, workdir, "audit-armed", env={
        "CYCLONUS_AUDIT": "1",
        "CYCLONUS_AUDIT_RATE": "1.0",
        "CYCLONUS_CHAOS": "verdict_corrupt:1",
        "CYCLONUS_FLIGHT_RECORDER_PATH": armed_dump,
    })
    try:
        detected_after = churn(srv, keys, armed_dump, check_budget)
    finally:
        srv.kill()
    if detected_after is None:
        raise AssertionError(
            f"armed verdict_corrupt went undetected through "
            f"{check_budget} checks (no audit-divergence dump)"
        )
    with open(armed_dump) as f:
        dumped = json.load(f)
    if dumped.get("reason") != "audit-divergence":
        raise AssertionError(
            f"divergence dump reason {dumped.get('reason')!r} "
            "(want 'audit-divergence')"
        )
    div_entries = [
        e for e in dumped.get("entries") or []
        if e.get("path") == "audit.divergence"
    ]
    if not div_entries:
        raise AssertionError("divergence dump carries no repro bundle")
    bundle = div_entries[-1]
    for field in ("query", "served", "oracle", "route", "epoch", "config"):
        if field not in bundle:
            raise AssertionError(f"repro bundle missing {field!r}")

    # phase 2: disarmed — the same churn must audit clean (no dump)
    clean_dump = os.path.join(workdir, "audit-clean.json")
    srv2 = _Serve(n_pods, n_ns, seed, workdir, "audit-clean", env={
        "CYCLONUS_AUDIT": "1",
        "CYCLONUS_AUDIT_RATE": "1.0",
        "CYCLONUS_CHAOS": "",
        "CYCLONUS_FLIGHT_RECORDER_PATH": clean_dump,
    })
    try:
        clean = churn(srv2, keys, clean_dump, min(check_budget, 8))
        rc = srv2.close()
    except Exception:
        srv2.kill()
        raise
    if rc != 0:
        raise AssertionError(f"disarmed serve exited rc={rc}")
    if clean is not None or os.path.exists(clean_dump):
        raise AssertionError(
            "disarmed run produced an audit-divergence dump — the "
            "sampler diverged with no injected fault"
        )
    return {
        "ok": True,
        "detected_after_checks": detected_after,
        "check_budget": check_budget,
        "bundle_route": bundle.get("route"),
        "bundle_epoch": bundle.get("epoch"),
        "dump": armed_dump,
    }


SCENARIOS = {
    "serve_kill_restart": scenario_serve_kill_restart,
    "audit_divergence": scenario_audit_divergence,
    "slo_ttfv": scenario_slo_ttfv,
    "poisoned_caches": scenario_poisoned_caches,
    "backend_init_flake": scenario_backend_init_flake,
    "worker_wire": scenario_worker_wire,
    "delta_drop": scenario_delta_drop,
}


def run_all(
    seed: int = 0,
    only: Optional[List[str]] = None,
    bound_s: float = 420.0,
) -> Dict:
    """Run the (selected) scenarios, each bounded; returns the suite
    report with per-scenario results and the overall ok flag."""
    from ..utils.bounded import run_bounded

    names = only or list(SCENARIOS)
    out: Dict = {"seed": seed, "scenarios": {}, "ok": True}
    for name in names:
        fn = SCENARIOS[name]
        t0 = time.perf_counter()
        status, value = run_bounded(lambda f=fn: f(seed=seed), bound_s)
        if status == "ok":
            report = value
        else:
            report = {
                "ok": False,
                "error": (
                    f"scenario exceeded the {bound_s:g}s bound"
                    if status == "timeout"
                    else f"{type(value).__name__}: {value}"
                ),
            }
        report["seconds"] = round(time.perf_counter() - t0, 3)
        out["scenarios"][name] = report
        out["ok"] = out["ok"] and bool(report.get("ok"))
    return out
