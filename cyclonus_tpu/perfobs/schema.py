"""The normalized run record every perf artifact reduces to.

A BENCH wrapper ({n, cmd, rc, tail, parsed}), a bare bench JSON line
(tools/tunnel_wait.py round artifacts), and a MULTICHIP dryrun wrapper
all become one PerfRun, so the sentinel and the report never reason
about file formats — only about runs.

failure_class is the load-bearing field.  The five classes partition
every observed round outcome:

  ok              the run produced a positive rate
  backend_init    the backend/compile service answered but failed
                  (r03: "TPU backend setup/compile error (Unavailable)")
  tunnel          the tunnel never answered — init join timeout, dead
                  probe, or an rc=124 hang with no output past backend
                  discovery (r04: "TPU tunnel dead or chip held")
  watchdog_stall  bench.py's own watchdog fired inside a phase
  engine          everything else: a real crash or wrong-verdict raise
                  in the measured pipeline

backend_init and tunnel are INFRA_CLASSES: the sentinel reports and
gates them separately from engine regressions, because a flaky tunnel
polluting the trajectory is exactly how rounds 3-4 lost their
scoreboard.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

FAILURE_CLASSES: Tuple[str, ...] = (
    "ok",
    "backend_init",
    "tunnel",
    "watchdog_stall",
    "engine",
)

#: failure classes attributable to infrastructure (cold-start / tunnel),
#: never to the measured engine — gated separately by the sentinel
INFRA_CLASSES: Tuple[str, ...] = ("backend_init", "tunnel")


@dataclass
class PerfRun:
    """One benchmark (or multichip dryrun) run, normalized."""

    run_id: str  # "r03", "watchdog-20260731-104401", ...
    kind: str  # "bench" | "multichip"
    source: str  # path the run was ingested from
    failure_class: str  # one of FAILURE_CLASSES
    ok: bool
    n: Optional[int] = None  # round number when the wrapper carries one
    rc: Optional[int] = None
    cells_per_sec: float = 0.0
    cells_per_sec_per_chip: Optional[float] = None
    # per-chip rate at max devices / single-device rate of the SAME
    # workload (a mesh_scaling block with both rows) — the only
    # apples-to-apples efficiency; rates from different problem sizes
    # are never divided into each other
    scaling_efficiency: Optional[float] = None
    n_devices: Optional[int] = None
    virtual_mesh: bool = False  # per-chip rate from a virtual CPU mesh
    # detail.mesh row fields of the overlapped ring path (None: older
    # artifact or leg skipped).  Report-only for now, like the serve
    # fields: the scaling-efficiency gate above is the gated surface.
    mesh_ring_step_s: Optional[float] = None
    mesh_overlap_efficiency: Optional[float] = None
    warmup_s: Optional[float] = None
    # normalized per-phase wall-clock seconds: detail.phase_history_s
    # merged with the named detail.*_s timings (build/encode/...)
    phases: Dict[str, float] = field(default_factory=dict)
    # detail.warmup_phases — the span-registry breakdown of warmup_s
    warmup_phases: Dict[str, float] = field(default_factory=dict)
    # flattened scalar counters/gauges from detail.telemetry.metrics
    telemetry_counters: Dict[str, float] = field(default_factory=dict)
    # cold-start forensics: backend-init attempts, backoff, outcome
    retries: Dict[str, Any] = field(default_factory=dict)
    # detail.class_compression.ratio — pods/classes of the headline
    # engine's equivalence-class grid compression (None: not recorded
    # or compression inactive).  The sentinel WARNS (never fails) when
    # it degrades >2x vs the baseline best on the same workload.
    class_compression_ratio: Optional[float] = None
    # detail.serve — the verdict-service churn leg (None: leg skipped
    # or an older artifact).  Warn-only in the sentinel for now, like
    # class_compression_ratio: the leg's own hard assertions (strict
    # incremental mode + the differential gate) already fail the bench
    # on correctness, so these fields gate only trends.
    serve_incremental_apply_s: Optional[float] = None
    serve_full_rebuild_s: Optional[float] = None
    serve_queries_per_sec: Optional[float] = None
    # detail.serve SLO fields (None: leg skipped or an older artifact).
    # Warn-only in the sentinel like the other serve fields: a rising
    # shed rate or a sinking budget under the SAME churn workload is a
    # latency regression the p99 gate may smooth over.
    serve_shed_rate: Optional[float] = None
    serve_slo_budget_remaining: Optional[float] = None
    # detail.tiers — the precedence-tier bench leg (None/False: leg
    # skipped or an older artifact).  Warn-only in the sentinel like
    # class_compression_ratio: the leg's own oracle spot-parity
    # assertion already fails the bench on correctness, so resolve_s
    # gates only trends.
    tiers_active: bool = False
    tiers_anp_count: Optional[int] = None
    tiers_resolve_s: Optional[float] = None
    # detail.cidr — the TSS/LPM CIDR pre-classification leg (None/False:
    # leg skipped or an older artifact).  Warn-only in the sentinel like
    # class_compression_ratio: the leg's own throughput assertion and
    # oracle spot parity already fail the bench on correctness, so
    # lpm_s gates only trends (>2x degradation vs baseline best warns).
    cidr_active: bool = False
    cidr_distinct: Optional[int] = None
    cidr_partitions: Optional[int] = None
    cidr_classes: Optional[int] = None
    cidr_ratio: Optional[float] = None
    cidr_lpm_s: Optional[float] = None
    # detail.roofline.efficiency_vs_roofline — measured eval vs the
    # analytic limit for the shapes it ran (None: older artifact or
    # roofline skipped).  Gated >= min_roofline_efficiency on NEW runs
    # only (pack_active not None marks them); the committed BENCH_r0*
    # fixtures predate detail.pack and keep ingesting/gating unchanged.
    roofline_efficiency: Optional[float] = None
    # detail.pack — the bit-packed dtype plan (None everywhere: older
    # artifact).  pack_active is the new-run marker the sentinel keys
    # its efficiency gate and hard rate floor on.
    pack_active: Optional[bool] = None
    pack_dtype: Optional[str] = None
    pack_tile: Optional[List[int]] = None  # tuned [bs, bd] winner
    pack_search_s: Optional[float] = None
    pack_candidates: Optional[int] = None
    # detail.cold_start.aot_cache — persistent AOT executable-cache
    # forensics (None: older artifact).  aot_adopted > 0 marks a
    # CACHE-BEARING run: the sentinel graduates warmup_s from the
    # warn-tolerance relative bound to a HARD absolute bound on exactly
    # these runs (a restarted process that adopted its executables has
    # no compile storm left to excuse a long warmup).
    aot_hits: Optional[int] = None
    aot_misses: Optional[int] = None
    aot_adopted: Optional[int] = None
    aot_compiles: Optional[int] = None
    # detail.chaos — the serve kill/restart leg's time-to-first-verdict
    # (None: leg skipped or an older artifact).  Warn-only in the
    # sentinel (new fields ride warn-only first); the bench leg itself
    # hard-bounds it via CYCLONUS_CHAOS_TTFV_S.
    chaos_ttfv_s: Optional[float] = None
    # detail.audit — the verdict audit plane's per-run accounting
    # (None: auditing disabled, leg skipped, or an older artifact).
    # Warn-only in the sentinel like the other serve fields — EXCEPT
    # that any nonzero audit_diverged gets its own note: a divergence
    # is a correctness signal, not a trend.
    audit_checked: Optional[int] = None
    audit_diverged: Optional[int] = None
    audit_digest_s: Optional[float] = None
    # detail.wire — the wire-protocol generation the run spoke and the
    # bench's live skew-sweep census (None: an older artifact).  Warn-
    # only in the sentinel: a schema_version bump across rounds is a
    # deliberate protocol change worth a human note, never a perf fail
    # (wirelint's WR003 golden gate is the hard check).
    wire_schema_version: Optional[int] = None
    wire_keys: Optional[int] = None
    wire_skew_pairs: Optional[int] = None
    error: Optional[str] = None
    metric: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "run_id": self.run_id,
            "kind": self.kind,
            "source": self.source,
            "failure_class": self.failure_class,
            "ok": self.ok,
            "n": self.n,
            "rc": self.rc,
            "cells_per_sec": self.cells_per_sec,
            "cells_per_sec_per_chip": self.cells_per_sec_per_chip,
            "scaling_efficiency": self.scaling_efficiency,
            "n_devices": self.n_devices,
            "virtual_mesh": self.virtual_mesh,
            "mesh_ring_step_s": self.mesh_ring_step_s,
            "mesh_overlap_efficiency": self.mesh_overlap_efficiency,
            "warmup_s": self.warmup_s,
            "phases": dict(self.phases),
            "warmup_phases": dict(self.warmup_phases),
            "telemetry_counters": dict(self.telemetry_counters),
            "retries": dict(self.retries),
            "class_compression_ratio": self.class_compression_ratio,
            "serve_incremental_apply_s": self.serve_incremental_apply_s,
            "serve_full_rebuild_s": self.serve_full_rebuild_s,
            "serve_queries_per_sec": self.serve_queries_per_sec,
            "serve_shed_rate": self.serve_shed_rate,
            "serve_slo_budget_remaining": self.serve_slo_budget_remaining,
            "tiers_active": self.tiers_active,
            "tiers_anp_count": self.tiers_anp_count,
            "tiers_resolve_s": self.tiers_resolve_s,
            "cidr_active": self.cidr_active,
            "cidr_distinct": self.cidr_distinct,
            "cidr_partitions": self.cidr_partitions,
            "cidr_classes": self.cidr_classes,
            "cidr_ratio": self.cidr_ratio,
            "cidr_lpm_s": self.cidr_lpm_s,
            "roofline_efficiency": self.roofline_efficiency,
            "pack_active": self.pack_active,
            "pack_dtype": self.pack_dtype,
            "pack_tile": self.pack_tile,
            "pack_search_s": self.pack_search_s,
            "pack_candidates": self.pack_candidates,
            "aot_hits": self.aot_hits,
            "aot_misses": self.aot_misses,
            "aot_adopted": self.aot_adopted,
            "aot_compiles": self.aot_compiles,
            "chaos_ttfv_s": self.chaos_ttfv_s,
            "audit_checked": self.audit_checked,
            "audit_diverged": self.audit_diverged,
            "audit_digest_s": self.audit_digest_s,
            "wire_schema_version": self.wire_schema_version,
            "wire_keys": self.wire_keys,
            "wire_skew_pairs": self.wire_skew_pairs,
            "error": self.error,
            "metric": self.metric,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "PerfRun":
        if d.get("failure_class") not in FAILURE_CLASSES:
            raise ValueError(
                f"unknown failure_class {d.get('failure_class')!r} "
                f"(expected one of {FAILURE_CLASSES})"
            )
        return cls(**d)

    @property
    def is_infra_failure(self) -> bool:
        return self.failure_class in INFRA_CLASSES

    def sort_key(self) -> Tuple[int, str]:
        """Chronological-ish order: wrapper round number first, then
        run_id (timestamped watchdog artifacts sort lexically)."""
        return (self.n if self.n is not None else 1 << 30, self.run_id)


def flatten_metric_samples(metrics: Dict[str, Any]) -> Dict[str, float]:
    """detail.telemetry.metrics -> {family or family{k=v}: value} for
    scalar (counter/gauge) samples.  Histograms are skipped — the ledger
    keeps the counters the gate and report actually read."""
    out: Dict[str, float] = {}
    for name, fam in sorted((metrics or {}).items()):
        if not isinstance(fam, dict) or fam.get("type") == "histogram":
            continue
        for sample in fam.get("samples", []):
            labels = sample.get("labels") or {}
            if labels:
                inner = ",".join(
                    f"{k}={v}" for k, v in sorted(labels.items())
                )
                key = f"{name}{{{inner}}}"
            else:
                key = name
            try:
                out[key] = float(sample["value"])
            except (KeyError, TypeError, ValueError):
                continue
    return out


def phase_map(history: Optional[List[Any]]) -> Dict[str, float]:
    """detail.phase_history_s ([["startup", 0.08], ...]) -> {phase: s},
    summing repeated visits (compiled_parity re-enters its phase)."""
    out: Dict[str, float] = {}
    for item in history or []:
        try:
            name, seconds = item[0], float(item[1])
        except (TypeError, ValueError, IndexError):
            continue
        out[str(name)] = out.get(str(name), 0.0) + seconds
    return out
