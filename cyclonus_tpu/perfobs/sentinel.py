"""The noise-aware regression sentinel (`cyclonus-tpu perf gate`).

Gate posture, in order of precedence for the candidate (latest) run:

  1. infra flake (failure_class backend_init | tunnel): reported with
     the cold-start forensics (phase of death, retry counts) and gated
     SEPARATELY — exit code 2, or 0 under --allow-infra.  Never counted
     as an engine regression, and never admitted into baselines.
  2. engine-side failure (watchdog_stall | engine): exit 1 — the run
     died inside the measured pipeline.
  3. healthy candidate: compared against min-of-N baselines built from
     the last N prior HEALTHY runs only:
       - cells_per_sec   >= best-of-N * (1 - rate_tol)
       - warmup_s        <= best-of-N * (1 + warmup_tol) + warmup_slack
                         (HARD absolute ceiling warmup_cached_max_s
                         instead on cache-bearing runs: aot_adopted > 0)
       - each phase      <= best-of-N * (1 + phase_tol) + phase_slack
       - scaling         cells_per_sec_per_chip / single-chip best
                         >= min_scaling_efficiency (real meshes only:
                         virtual CPU-mesh rates share one core and are
                         reported, never gated)
     Any violated bound is an engine regression: exit 1, with a delta
     report NAMING the offending metric/phase.

Min-of-N ("best of the last N") is the noise model: tunneled-chip
timings jitter +-30% run to run (bench.py min-of-5 exists for the same
reason), so a bound keyed to the mean would either flap or need a
tolerance wide enough to hide real regressions.  The best-of window
plus a relative tolerance plus a small absolute slack (for
near-zero phases) tracks the envelope instead.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .ledger import Ledger
from .schema import PerfRun

#: phases the generic per-phase rule skips: warmup/eval have dedicated
#: metrics (one regression, one finding), backend_init_join is an
#: INFRA wait (attach time on a cold/contended tunnel) — gating it as
#: an engine regression would recreate the r03/r04 confusion; the
#: cold-start forensics and failure classes cover it instead — and
#: serve_churn has its own warn-only fields (serve_incremental_apply_s
#: / serve_queries_per_sec) whose workload knobs may differ per round,
#: and tiers likewise rides warn-only (tiers_resolve_s; BENCH_TIERS_*
#: knobs shape the leg)
_DEDICATED_PHASES = frozenset(
    {"warmup", "eval", "backend_init_join", "serve_churn", "tiers",
     "cidr", "chaos"}
)


@dataclass
class Delta:
    """One gated comparison; `regressed` makes it a finding."""

    metric: str  # "cells_per_sec", "warmup_s", "phase:encode", ...
    candidate: float
    baseline: float  # best-of-N
    bound: float
    regressed: bool
    direction: str  # "min" (higher is better) | "max" (lower is better)
    baseline_runs: List[str] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "metric": self.metric,
            "candidate": self.candidate,
            "baseline": self.baseline,
            "bound": self.bound,
            "regressed": self.regressed,
            "direction": self.direction,
            "baseline_runs": list(self.baseline_runs),
        }


@dataclass
class GateResult:
    status: str  # "pass" | "engine_regression" | "infra_flake" | "no_data"
    candidate: Optional[str]  # run id
    deltas: List[Delta] = field(default_factory=list)
    infra: Dict[str, Any] = field(default_factory=dict)
    notes: List[str] = field(default_factory=list)

    @property
    def exit_code(self) -> int:
        return {"pass": 0, "no_data": 0, "infra_flake": 2}.get(self.status, 1)

    @property
    def regressions(self) -> List[Delta]:
        return [d for d in self.deltas if d.regressed]

    def to_dict(self) -> Dict[str, Any]:
        return {
            "status": self.status,
            "candidate": self.candidate,
            "exit_code": self.exit_code,
            "deltas": [d.to_dict() for d in self.deltas],
            "infra": dict(self.infra),
            "notes": list(self.notes),
        }

    def report(self) -> str:
        """The delta report: one line per gated metric, offenders
        first and flagged, so the failing phase is named in the first
        screenful of CI output."""
        lines = [f"perf gate: {self.status.upper()} (candidate {self.candidate})"]
        for note in self.notes:
            lines.append(f"  note: {note}")
        if self.infra:
            fr = self.infra
            lines.append(
                f"  infra: class={fr.get('failure_class')} "
                f"phase={fr.get('died_in_phase')} "
                f"attempts={fr.get('attempts')} error={fr.get('error')}"
            )
        for d in sorted(self.deltas, key=lambda d: not d.regressed):
            mark = "REGRESSED" if d.regressed else "ok"
            cmp_ = ">=" if d.direction == "min" else "<="
            lines.append(
                f"  [{mark}] {d.metric}: candidate={d.candidate:g} "
                f"{cmp_} bound={d.bound:g} "
                f"(best-of-{len(d.baseline_runs)} baseline={d.baseline:g} "
                f"from {','.join(d.baseline_runs) or '-'})"
            )
        return "\n".join(lines)


def _died_in_phase(run: PerfRun) -> Optional[str]:
    """The last phase of the wall-clock history = where the run died."""
    if not run.phases:
        return None
    # phases is insertion-ordered from phase_history_s; the named
    # detail keys only exist on successful runs
    return list(run.phases)[-1]


def gate(
    ledger: Ledger,
    *,
    baseline_n: int = 3,
    rate_tol: float = 0.30,
    warmup_tol: float = 0.50,
    warmup_slack_s: float = 2.0,
    phase_tol: float = 0.50,
    phase_slack_s: float = 2.0,
    min_scaling_efficiency: float = 0.5,
    min_roofline_efficiency: float = 0.7,
    warmup_cached_max_s: float = 5.0,
    candidate: Optional[PerfRun] = None,
) -> GateResult:
    """Gate the candidate (default: latest bench run) against the
    baselines formed by the prior healthy runs."""
    bench = ledger.bench_runs()
    if candidate is None:
        candidate = bench[-1] if bench else None
    if candidate is None:
        return GateResult(
            status="no_data",
            candidate=None,
            notes=["no bench runs ingested — nothing to gate"],
        )

    priors = [
        r
        for r in bench
        if r.failure_class == "ok" and r.sort_key() < candidate.sort_key()
    ]
    baselines = priors[-baseline_n:]
    base_ids = [r.run_id for r in baselines]

    infra_counts = {
        k: v for k, v in ledger.counts_by_class().items() if v
    }
    notes = [f"history: {infra_counts}"]

    if candidate.is_infra_failure:
        return GateResult(
            status="infra_flake",
            candidate=candidate.run_id,
            infra={
                "failure_class": candidate.failure_class,
                "died_in_phase": _died_in_phase(candidate),
                "attempts": candidate.retries.get("attempts"),
                "backoff_s": candidate.retries.get("backoff_s"),
                "error": candidate.error,
            },
            notes=notes
            + [
                "infra flake, NOT an engine regression — the engine "
                "was never reached; trajectory baselines are unchanged"
            ],
        )
    if candidate.failure_class in ("watchdog_stall", "engine"):
        return GateResult(
            status="engine_regression",
            candidate=candidate.run_id,
            infra={
                "failure_class": candidate.failure_class,
                "died_in_phase": _died_in_phase(candidate),
                "error": candidate.error,
            },
            notes=notes + ["run failed inside the measured pipeline"],
        )

    deltas: List[Delta] = []
    if not baselines:
        notes.append(
            "no healthy prior runs — candidate admitted as the first baseline"
        )

    # --- throughput: higher is better, best-of-N baseline ---------------
    # NEW-FORMAT runs (detail.pack present -> pack_active not None) gate
    # against the min-of-N best as a HARD FLOOR: the bit-packed kernel's
    # acceptance is "at least the old rate", so the 30% noise tolerance
    # that protects legacy trend gating would hide exactly the
    # regression the floor exists to catch.  Legacy artifacts (the
    # committed BENCH_r0* fixtures) keep the tolerant bound unchanged.
    rates = [r.cells_per_sec for r in baselines if r.cells_per_sec > 0]
    if rates and candidate.cells_per_sec > 0:
        best = max(rates)
        hard_floor = candidate.pack_active is not None
        bound = best if hard_floor else best * (1.0 - rate_tol)
        deltas.append(
            Delta(
                metric="cells_per_sec"
                + ("[hard-floor]" if hard_floor else ""),
                candidate=candidate.cells_per_sec,
                baseline=best,
                bound=bound,
                regressed=candidate.cells_per_sec < bound,
                direction="min",
                baseline_runs=base_ids,
            )
        )

    # --- roofline efficiency: the bit-packed kernel's headline gate -----
    # measured eval vs the analytic limit for its own shapes (bench
    # detail.roofline).  Gated on new-format runs only: legacy fixtures
    # carry the field (r05: 0.433) but predate the packed kernel, and
    # retroactively failing them would poison the whole trajectory.
    if candidate.pack_active is not None and isinstance(
        candidate.roofline_efficiency, (int, float)
    ):
        deltas.append(
            Delta(
                metric="roofline_efficiency",
                candidate=candidate.roofline_efficiency,
                baseline=1.0,
                bound=min_roofline_efficiency,
                regressed=candidate.roofline_efficiency
                < min_roofline_efficiency,
                direction="min",
                baseline_runs=[candidate.run_id],
            )
        )
    elif candidate.pack_active is not None:
        notes.append(
            "roofline: new-format run without an efficiency figure — "
            "the >=%g gate was skipped (roofline leg missing?)"
            % min_roofline_efficiency
        )

    # --- warmup: lower is better, min-of-N baseline ---------------------
    warmups = [
        r.warmup_s for r in baselines if isinstance(r.warmup_s, (int, float))
    ]
    if warmups and isinstance(candidate.warmup_s, (int, float)):
        best = min(warmups)
        bound = best * (1.0 + warmup_tol) + warmup_slack_s
        deltas.append(
            Delta(
                metric="warmup_s",
                candidate=candidate.warmup_s,
                baseline=best,
                bound=bound,
                regressed=candidate.warmup_s > bound,
                direction="max",
                baseline_runs=base_ids,
            )
        )
    # --- warmup on CACHE-BEARING runs: graduated to a HARD bound ---------
    # a run whose detail.cold_start.aot_cache (snapshotted at END OF
    # WARMUP — later bench legs adopting the process's own stores must
    # not count) shows adopted executables AND zero fresh compiles
    # restarted against a FULLY warm persistent cache: its warmup has
    # no trace/compile storm left, so it gets an ABSOLUTE ceiling
    # (warmup_cached_max_s) instead of the tolerance-padded relative
    # bound above — the cold-start acceptance criterion.  A half-warm
    # cache (adopted > 0 but compiles > 0) legitimately pays some
    # compiles and keeps the relative posture, as do legacy artifacts
    # with no aot_cache block at all.
    if (
        isinstance(candidate.aot_adopted, int)
        and candidate.aot_adopted > 0
        and (candidate.aot_compiles or 0) == 0
        and isinstance(candidate.warmup_s, (int, float))
    ):
        deltas.append(
            Delta(
                metric="warmup_s[aot-cached]",
                candidate=candidate.warmup_s,
                baseline=warmup_cached_max_s,
                bound=warmup_cached_max_s,
                regressed=candidate.warmup_s > warmup_cached_max_s,
                direction="max",
                baseline_runs=[candidate.run_id],
            )
        )

    # --- chaos restart leg: WARN, never fail ----------------------------
    # time-to-first-verdict after a kill/restart is hard-bounded INSIDE
    # the bench leg (CYCLONUS_CHAOS_TTFV_S raises there); here the new
    # field rides warn-only first, the serve-field discipline
    ttfv_base = [
        r.chaos_ttfv_s
        for r in baselines
        if isinstance(r.chaos_ttfv_s, (int, float))
    ]
    if ttfv_base and isinstance(candidate.chaos_ttfv_s, (int, float)):
        best_ttfv = min(ttfv_base)
        if candidate.chaos_ttfv_s > 2.0 * best_ttfv:
            notes.append(
                "WARNING: chaos time-to-first-verdict degraded >2x vs "
                f"baseline: candidate {candidate.chaos_ttfv_s:g}s vs "
                f"best {best_ttfv:g}s — reported only (warn, not "
                "fail); check the AOT cache adoption path before the "
                "next round"
            )

    # --- class compression ratio: WARN, never fail ----------------------
    # the ratio is workload-shaped (a cluster with genuinely more label
    # diversity legitimately compresses less), so a degradation is a
    # note for a human, not a regression — the cells/s gate above
    # already covers any real perf impact of a lost compression
    ratios = [
        r.class_compression_ratio
        for r in baselines
        if isinstance(r.class_compression_ratio, (int, float))
    ]
    if ratios and isinstance(
        candidate.class_compression_ratio, (int, float)
    ):
        best_ratio = max(ratios)
        if candidate.class_compression_ratio < best_ratio / 2.0:
            notes.append(
                "WARNING: class_compression_ratio degraded >2x vs "
                f"baseline: candidate "
                f"{candidate.class_compression_ratio:g} vs best "
                f"{best_ratio:g} — reported only (warn, not fail); "
                "check the encoding/class signature before the next "
                "large-cluster run"
            )

    # --- verdict-service churn leg: WARN, never fail --------------------
    # new fields ride warn-only first (like class_compression_ratio):
    # the serve leg's own hard assertions already fail the bench on
    # correctness, and the leg's workload knobs (BENCH_SERVE_*) may
    # legitimately differ across rounds — a degradation is a note, and
    # these graduate to gated bounds once a few healthy rounds exist
    apply_base = [
        r.serve_incremental_apply_s
        for r in baselines
        if isinstance(r.serve_incremental_apply_s, (int, float))
    ]
    if apply_base and isinstance(
        candidate.serve_incremental_apply_s, (int, float)
    ):
        best_apply = min(apply_base)
        if candidate.serve_incremental_apply_s > 2.0 * best_apply:
            notes.append(
                "WARNING: serve_incremental_apply_s degraded >2x vs "
                f"baseline: candidate "
                f"{candidate.serve_incremental_apply_s:g}s vs best "
                f"{best_apply:g}s — reported only (warn, not fail); "
                "check the serve patch path before the next round"
            )
    qps_base = [
        r.serve_queries_per_sec
        for r in baselines
        if isinstance(r.serve_queries_per_sec, (int, float))
    ]
    if qps_base and isinstance(
        candidate.serve_queries_per_sec, (int, float)
    ):
        best_qps = max(qps_base)
        if candidate.serve_queries_per_sec < best_qps / 2.0:
            notes.append(
                "WARNING: serve_queries_per_sec degraded >2x vs "
                f"baseline: candidate "
                f"{candidate.serve_queries_per_sec:g}/s vs best "
                f"{best_qps:g}/s — reported only (warn, not fail)"
            )
    shed_base = [
        r.serve_shed_rate
        for r in baselines
        if isinstance(r.serve_shed_rate, (int, float))
    ]
    if shed_base and isinstance(candidate.serve_shed_rate, (int, float)):
        best_shed = min(shed_base)
        if (
            candidate.serve_shed_rate > 0.01
            and candidate.serve_shed_rate > 2.0 * max(best_shed, 0.005)
        ):
            notes.append(
                "WARNING: serve_shed_rate rose >2x vs baseline: "
                f"candidate {candidate.serve_shed_rate:g} vs best "
                f"{best_shed:g} — reported only (warn, not fail); the "
                "churn leg is shedding queries the baseline answered — "
                "check query_p99 before the next round"
            )
    budget_base = [
        r.serve_slo_budget_remaining
        for r in baselines
        if isinstance(r.serve_slo_budget_remaining, (int, float))
    ]
    if budget_base and isinstance(
        candidate.serve_slo_budget_remaining, (int, float)
    ):
        best_budget = max(budget_base)
        if candidate.serve_slo_budget_remaining < best_budget / 2.0:
            notes.append(
                "WARNING: serve_slo_budget_remaining sank >2x vs "
                f"baseline: candidate "
                f"{candidate.serve_slo_budget_remaining:g} vs best "
                f"{best_budget:g} — reported only (warn, not fail); "
                "the query_p99 error budget is burning faster under "
                "the same churn workload"
            )

    # --- audit plane: WARN, never fail ----------------------------------
    # a nonzero divergence count is a CORRECTNESS signal, not a trend —
    # but the bench leg's own assertions (and tests/test_audit.py) are
    # the hard gate; here it rides warn-only like the serve fields so
    # one flaky artifact can't block a perf round
    if (
        isinstance(candidate.audit_diverged, int)
        and candidate.audit_diverged > 0
    ):
        notes.append(
            "WARNING: audit plane observed "
            f"{candidate.audit_diverged} shadow-oracle divergence(s) "
            f"across {candidate.audit_checked or 0} checks — reported "
            "only (warn, not fail); open the audit-divergence "
            "flight-recorder bundle before trusting this round's "
            "verdicts"
        )

    # --- wire protocol generation: WARN, never fail ---------------------
    # a schema_version change between the candidate and its baselines is
    # a deliberate, golden-regenerating protocol change (wirelint WR003
    # and `make skewharness` are the hard gates) — but perf numbers
    # straddling a protocol bump deserve a human note, since the serve
    # leg's reply shape (and so its byte volume) changed with it
    wire_base = [
        r.wire_schema_version
        for r in baselines
        if isinstance(r.wire_schema_version, int)
    ]
    if (
        isinstance(candidate.wire_schema_version, int)
        and wire_base
        and candidate.wire_schema_version != max(wire_base)
    ):
        notes.append(
            "NOTE: wire protocol generation changed "
            f"(schema_version {max(wire_base)} -> "
            f"{candidate.wire_schema_version}, "
            f"{candidate.wire_keys or 0} registered keys, "
            f"{candidate.wire_skew_pairs or 0} skew pairs swept) — "
            "baselines predate the protocol change; reported only "
            "(warn, not fail)"
        )

    # --- precedence-tier leg: WARN, never fail --------------------------
    # same discipline as serve: the leg's oracle spot-parity assertion
    # already fails the bench on correctness, and BENCH_TIERS_* knobs
    # may legitimately differ per round — resolve_s degradation is a
    # note for a human
    resolve_base = [
        r.tiers_resolve_s
        for r in baselines
        if isinstance(r.tiers_resolve_s, (int, float))
    ]
    if resolve_base and isinstance(
        candidate.tiers_resolve_s, (int, float)
    ):
        best_resolve = min(resolve_base)
        if candidate.tiers_resolve_s > 2.0 * best_resolve:
            notes.append(
                "WARNING: tiers_resolve_s degraded >2x vs baseline: "
                f"candidate {candidate.tiers_resolve_s:g}s vs best "
                f"{best_resolve:g}s — reported only (warn, not fail); "
                "check the tier resolution epilogue before the next "
                "round"
            )

    # --- CIDR TSS leg: WARN, never fail ---------------------------------
    # same posture class_compression_ratio took when it landed: the
    # leg's own dense-vs-TSS throughput assertion and oracle spot
    # parity already fail the bench on correctness, so the LPM stage
    # wall-clock gates only trends
    lpm_base = [
        r.cidr_lpm_s
        for r in baselines
        if isinstance(r.cidr_lpm_s, (int, float))
    ]
    if lpm_base and isinstance(candidate.cidr_lpm_s, (int, float)):
        best_lpm = min(lpm_base)
        if candidate.cidr_lpm_s > 2.0 * best_lpm:
            notes.append(
                "WARNING: cidr_lpm_s degraded >2x vs baseline: "
                f"candidate {candidate.cidr_lpm_s:g}s vs best "
                f"{best_lpm:g}s — reported only (warn, not fail); "
                "check the LPM partition stage before the next round"
            )

    # --- per-phase bounds: every phase both sides know ------------------
    for phase, cand_s in sorted(candidate.phases.items()):
        if phase in _DEDICATED_PHASES:
            continue
        prior_s = [
            r.phases[phase] for r in baselines if phase in r.phases
        ]
        if not prior_s:
            continue
        best = min(prior_s)
        bound = best * (1.0 + phase_tol) + phase_slack_s
        deltas.append(
            Delta(
                metric=f"phase:{phase}",
                candidate=cand_s,
                baseline=best,
                bound=bound,
                regressed=cand_s > bound,
                direction="max",
                baseline_runs=base_ids,
            )
        )

    # --- multichip scaling efficiency -----------------------------------
    # cells/s-per-chip vs single-chip (ROADMAP item 3's missing gate),
    # with two hard rules about comparability:
    #   * efficiency is only ever computed WITHIN one workload — a
    #     mesh_scaling block's N-dev per-chip rate over its own 1-dev
    #     rate (PerfRun.scaling_efficiency, set at ingest).  A tiny
    #     multichip dryrun's rate divided by the 100k-pod headline
    #     would "regress" on problem size, not on scaling.
    #   * only REAL meshes gate: a virtual CPU mesh timeshares one
    #     core, so its per-chip rate divides by n_dev by construction.
    gated_scaling = False
    if candidate.scaling_efficiency is not None:
        if candidate.virtual_mesh:
            notes.append(
                "scaling: candidate efficiency "
                f"{candidate.scaling_efficiency:g} is from a VIRTUAL "
                "mesh — reported, not gated"
            )
        else:
            gated_scaling = True
            deltas.append(
                Delta(
                    metric=(
                        f"scaling_efficiency[{candidate.run_id}"
                        f"@{candidate.n_devices}chip]"
                    ),
                    candidate=candidate.scaling_efficiency,
                    baseline=1.0,
                    bound=min_scaling_efficiency,
                    regressed=candidate.scaling_efficiency
                    < min_scaling_efficiency,
                    direction="min",
                    baseline_runs=[candidate.run_id],
                )
            )
    # trend leg: the latest REAL multichip per-chip rate against prior
    # real multichip runs at the SAME device count (same dryrun
    # workload) — min-of-N like the headline rate
    mc_real = [
        r
        for r in ledger.multichip_runs()
        if r.cells_per_sec_per_chip is not None and not r.virtual_mesh
    ]
    if mc_real:
        mc = mc_real[-1]
        gated_scaling = True
        mc_priors = [
            r.cells_per_sec_per_chip
            for r in mc_real[:-1]
            if r.n_devices == mc.n_devices
        ][-baseline_n:]
        if mc_priors:
            best_mc = max(mc_priors)
            bound = best_mc * (1.0 - rate_tol)
            deltas.append(
                Delta(
                    metric=(
                        f"cells_per_sec_per_chip[{mc.run_id}"
                        f"@{mc.n_devices}chip]"
                    ),
                    candidate=mc.cells_per_sec_per_chip,
                    baseline=best_mc,
                    bound=bound,
                    regressed=mc.cells_per_sec_per_chip < bound,
                    direction="min",
                    baseline_runs=[
                        r.run_id
                        for r in mc_real[:-1]
                        if r.n_devices == mc.n_devices
                    ][-baseline_n:],
                )
            )
        else:
            notes.append(
                f"scaling: {mc.run_id} is the first real multichip "
                f"run at {mc.n_devices} devices — admitted as baseline"
            )
    if not gated_scaling:
        if any(
            r.cells_per_sec_per_chip is not None for r in ledger.runs
        ):
            notes.append(
                "scaling: all recorded per-chip rates are from VIRTUAL "
                "meshes — reported, not gated"
            )
        else:
            notes.append(
                "scaling: no multichip per-chip rate recorded yet — "
                "gate skipped (runs record cells_per_sec_per_chip "
                "from now on)"
            )

    status = (
        "engine_regression"
        if any(d.regressed for d in deltas)
        else "pass"
    )
    return GateResult(
        status=status,
        candidate=candidate.run_id,
        deltas=deltas,
        notes=notes,
    )
