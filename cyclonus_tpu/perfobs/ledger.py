"""Ingest perf artifacts into the ledger, classifying every failure.

Three artifact shapes exist in the wild, and all of them must ingest
UNCHANGED (the five BENCH_r0*.json / MULTICHIP_r0*.json blobs in the
repo root are the acceptance fixtures):

  * the driver wrapper: {"n", "cmd", "rc", "tail", "parsed"} — parsed
    is the bench's final JSON line, or null when the run died without
    one (r03: rc=124, only the backend warning on stdout);
  * a bare bench JSON line ({"metric", "value", ..., "detail"}), as
    written by tools/tunnel_wait.py round artifacts (plus bench_rc/at);
  * the MULTICHIP dryrun wrapper: {"n_devices", "rc", "ok", "tail"}.

Classification reads the EVIDENCE, not just the rc: an explicit
failure_class in the JSON (new bench runs) wins; otherwise the error
text and stdout tail are matched against the known cold-start
signatures, and an rc=124 hang that never printed anything past backend
discovery is attributed to the tunnel — the one component that hangs
silently — not to the engine.
"""

from __future__ import annotations

import glob as _glob
import json
import os
import re
from typing import Any, Dict, Iterable, List, Optional

from .schema import (
    FAILURE_CLASSES,
    PerfRun,
    flatten_metric_samples,
    phase_map,
)

# evidence -> class, checked in order; first match wins.  The tunnel
# signatures run before the backend ones because r04's message names
# both ("backend init did not complete ... TPU tunnel dead"): a join
# timeout means the tunnel never answered, which is a harder claim than
# "the backend misbehaved".
_TUNNEL_RE = re.compile(
    r"tunnel (?:is )?dead|tunnel dead|chip held by another process"
    r"|did not complete within BENCH_INIT_DEADLINE"
    # tunnel_wait's outer backstop firing means the bench's own
    # watchdogs never printed — a pre-import hang, i.e. the tunnel
    r"|exceeded the .*subprocess bound",
    re.IGNORECASE,
)
_BACKEND_RE = re.compile(
    r"backend init failed|backend setup/compile error"
    r"|TPU backend setup|UNAVAILABLE: TPU|libtpu version mismatch",
    re.IGNORECASE,
)
_WATCHDOG_RE = re.compile(r"watchdog|stalled \d+s in phase", re.IGNORECASE)


def classify(
    parsed: Optional[Dict[str, Any]],
    rc: Optional[int] = None,
    tail: str = "",
) -> str:
    """Map one artifact's evidence to a failure class."""
    parsed = parsed or {}
    explicit = parsed.get("failure_class")
    if explicit in FAILURE_CLASSES:
        return explicit
    error = str(parsed.get("error") or "")
    if not error and parsed.get("value", 0) and "value" in parsed:
        return "ok"
    evidence = error + "\n" + (tail or "")
    if _WATCHDOG_RE.search(error):
        return "watchdog_stall"
    if _TUNNEL_RE.search(evidence):
        return "tunnel"
    if _BACKEND_RE.search(evidence):
        return "backend_init"
    if "value" not in parsed and rc == 124:
        # killed by the driver without ever printing a bench JSON line
        # past backend discovery: engine failures crash loudly
        # (traceback, error JSON); only a wedged tunnel hangs silently
        # (rounds 3/4)
        return "tunnel"
    return "engine"


# canonical phase names for the named detail.*_s timings — these are
# the precise (min-of-N) measurements; phase_history_s adds the rest
_NAMED_PHASES = (
    ("build_s", "matcher_build"),
    ("encode_s", "encode"),
    ("backend_init_s", "backend_init_join"),
    ("warmup_s", "warmup"),
    ("eval_s", "eval"),
)


def _collapse(phases: Dict[str, float]) -> Dict[str, float]:
    """Dynamic phase names ("compiled_parity:2048x300:int8",
    "mesh_scaling:4dev") collapse to their family so baselines across
    runs compare like with like."""
    out: Dict[str, float] = {}
    for name, seconds in phases.items():
        family = name.split(":", 1)[0]
        out[family] = out.get(family, 0.0) + seconds
    return out


def _evidence_line(tail: str) -> Optional[str]:
    """The line of the stdout tail that carries the failure signature
    (falling back to the last non-empty line) — what the report quotes
    as the run's error."""
    lines = [l.strip() for l in (tail or "").splitlines() if l.strip()]
    for line in reversed(lines):
        if (
            _TUNNEL_RE.search(line)
            or _BACKEND_RE.search(line)
            or _WATCHDOG_RE.search(line)
        ):
            return line
    return lines[-1] if lines else None


def _run_id_for(path: str, n: Optional[int], kind: str) -> str:
    if n is not None and kind == "bench":
        return f"r{n:02d}"
    stem = os.path.splitext(os.path.basename(path))[0]
    return stem.lower()


def _bench_run_from_parsed(
    run: PerfRun, parsed: Dict[str, Any]
) -> PerfRun:
    """Fill a PerfRun from a bench JSON line (success or error)."""
    detail = parsed.get("detail") or {}
    run.metric = parsed.get("metric")
    run.error = parsed.get("error")
    try:
        run.cells_per_sec = float(parsed.get("value") or 0.0)
    except (TypeError, ValueError):
        run.cells_per_sec = 0.0
    phases = _collapse(phase_map(detail.get("phase_history_s")))
    for key, name in _NAMED_PHASES:
        if isinstance(detail.get(key), (int, float)):
            phases[name] = float(detail[key])
    run.phases = phases
    if isinstance(detail.get("warmup_s"), (int, float)):
        run.warmup_s = float(detail["warmup_s"])
    run.warmup_phases = {
        k: float(v)
        for k, v in (detail.get("warmup_phases") or {}).items()
        if isinstance(v, (int, float))
    }
    tel = detail.get("telemetry") or {}
    run.telemetry_counters = flatten_metric_samples(tel.get("metrics") or {})
    cold = detail.get("cold_start") or detail.get("retries") or {}
    if isinstance(cold, dict):
        run.retries = dict(cold)
        # persistent AOT executable-cache forensics: adopted > 0 is the
        # cache-bearing marker that arms the sentinel's HARD warmup
        # bound (older artifacts carry no aot_cache block and keep the
        # relative warn-tolerance bound)
        aot = cold.get("aot_cache")
        if isinstance(aot, dict):
            for src, dst in (
                ("hits", "aot_hits"),
                ("misses", "aot_misses"),
                ("adopted", "aot_adopted"),
                ("compiles", "aot_compiles"),
            ):
                if isinstance(aot.get(src), int):
                    setattr(run, dst, int(aot[src]))
    chaos = detail.get("chaos")
    if isinstance(chaos, dict) and isinstance(
        chaos.get("ttfv_s"), (int, float)
    ):
        run.chaos_ttfv_s = float(chaos["ttfv_s"])
    cc = detail.get("class_compression")
    if isinstance(cc, dict) and isinstance(cc.get("ratio"), (int, float)):
        run.class_compression_ratio = float(cc["ratio"])
    serve = detail.get("serve")
    if isinstance(serve, dict):
        if isinstance(serve.get("incremental_apply_s"), (int, float)):
            run.serve_incremental_apply_s = float(
                serve["incremental_apply_s"]
            )
        if isinstance(serve.get("full_rebuild_s"), (int, float)):
            run.serve_full_rebuild_s = float(serve["full_rebuild_s"])
        if isinstance(serve.get("queries_per_sec"), (int, float)):
            run.serve_queries_per_sec = float(serve["queries_per_sec"])
        if isinstance(serve.get("shed_rate"), (int, float)):
            run.serve_shed_rate = float(serve["shed_rate"])
        if isinstance(serve.get("slo_budget_remaining"), (int, float)):
            run.serve_slo_budget_remaining = float(
                serve["slo_budget_remaining"]
            )
    audit = detail.get("audit")
    if isinstance(audit, dict):
        if isinstance(audit.get("checked"), int):
            run.audit_checked = int(audit["checked"])
        if isinstance(audit.get("diverged"), int):
            run.audit_diverged = int(audit["diverged"])
        if isinstance(audit.get("digest_s"), (int, float)):
            run.audit_digest_s = float(audit["digest_s"])
    wire = detail.get("wire")
    if isinstance(wire, dict):
        if isinstance(wire.get("schema_version"), int):
            run.wire_schema_version = int(wire["schema_version"])
        if isinstance(wire.get("keys"), int):
            run.wire_keys = int(wire["keys"])
        if isinstance(wire.get("skew_pairs_checked"), int):
            run.wire_skew_pairs = int(wire["skew_pairs_checked"])
    tiers = detail.get("tiers")
    if isinstance(tiers, dict):
        run.tiers_active = bool(tiers.get("active"))
        if isinstance(tiers.get("anp_count"), int):
            run.tiers_anp_count = int(tiers["anp_count"])
        if isinstance(tiers.get("resolve_s"), (int, float)):
            run.tiers_resolve_s = float(tiers["resolve_s"])
    cidr = detail.get("cidr")
    if isinstance(cidr, dict):
        run.cidr_active = bool(cidr.get("active"))
        if isinstance(cidr.get("distinct_cidrs"), int):
            run.cidr_distinct = int(cidr["distinct_cidrs"])
        if isinstance(cidr.get("partitions"), int):
            run.cidr_partitions = int(cidr["partitions"])
        if isinstance(cidr.get("classes"), int):
            run.cidr_classes = int(cidr["classes"])
        if isinstance(cidr.get("ratio"), (int, float)):
            run.cidr_ratio = float(cidr["ratio"])
        if isinstance(cidr.get("lpm_s"), (int, float)):
            run.cidr_lpm_s = float(cidr["lpm_s"])
    roofline = detail.get("roofline")
    if isinstance(roofline, dict) and isinstance(
        roofline.get("efficiency_vs_roofline"), (int, float)
    ):
        run.roofline_efficiency = float(roofline["efficiency_vs_roofline"])
    # detail.pack — the bit-packed dtype plan block: its PRESENCE (not
    # its truth) marks a new-format run, which is what arms the
    # sentinel's efficiency gate and hard rate floor; the committed
    # BENCH_r0* fixtures predate it and keep their legacy gating
    pack = detail.get("pack")
    if isinstance(pack, dict) and "active" in pack:
        run.pack_active = bool(pack.get("active"))
        if isinstance(pack.get("dtype"), str):
            run.pack_dtype = pack["dtype"]
        winner = pack.get("winner")
        if isinstance(winner, dict) and isinstance(
            winner.get("bs"), int
        ) and isinstance(winner.get("bd"), int):
            run.pack_tile = [winner["bs"], winner["bd"]]
        tune = pack.get("autotune")
        if isinstance(tune, dict):
            if isinstance(tune.get("search_s"), (int, float)):
                run.pack_search_s = float(tune["search_s"])
            cands = tune.get("candidates")
            if isinstance(cands, list):
                run.pack_candidates = len(cands)
    # detail.mesh (the first-class overlapped-ring leg) and the legacy
    # detail.mesh_scaling block share one row schema and ONE parser —
    # the same _ingest_mesh_row the MULTICHIP dryrun tail goes through
    mesh = detail.get("mesh") or detail.get("mesh_scaling") or {}
    rows = [
        r
        for r in (mesh.get("rows") or [])
        if isinstance(r, dict)
        and isinstance(r.get("cells_per_sec_per_chip"), (int, float))
    ]
    if rows:
        # the stable field the scaling gate reads: the best per-chip
        # rate at the HIGHEST device count the run exercised
        n_dev = max(int(r.get("devices", 1)) for r in rows)
        top = max(
            (r for r in rows if int(r.get("devices", 1)) == n_dev),
            key=lambda r: float(r["cells_per_sec_per_chip"]),
        )
        _ingest_mesh_row(run, top, default_virtual=mesh.get("virtual", True))
        # efficiency needs SAME-workload endpoints: a 1-device row of
        # this very block is the only valid denominator (dividing by
        # the headline single-chip rate would compare different
        # problem sizes)
        one_dev = [
            float(r["cells_per_sec"])
            for r in rows
            if int(r.get("devices", 1)) == 1
            and isinstance(r.get("cells_per_sec"), (int, float))
        ]
        if one_dev and n_dev > 1:
            run.scaling_efficiency = round(
                run.cells_per_sec_per_chip / max(one_dev), 4
            )
    return run


def _ingest_mesh_row(
    run: PerfRun, row: Dict[str, Any], default_virtual: Any = True
) -> None:
    """Fold ONE mesh row — a detail.mesh rows[] entry or the MULTICHIP
    dryrun's tail JSON line (same schema by design) — into the PerfRun
    mesh fields.  The single parser both artifact shapes ingest
    through, so the dryrun and the bench leg can never drift."""
    if isinstance(row.get("cells_per_sec_per_chip"), (int, float)):
        run.cells_per_sec_per_chip = float(row["cells_per_sec_per_chip"])
    if isinstance(row.get("cells_per_sec"), (int, float)):
        # multichip runs carry no headline rate of their own; bench
        # runs already set run.cells_per_sec from the JSON line value
        if run.kind == "multichip" or run.cells_per_sec == 0.0:
            run.cells_per_sec = float(row["cells_per_sec"])
    if isinstance(row.get("devices"), int):
        run.n_devices = row["devices"]
    elif isinstance(row.get("n_devices"), int):
        run.n_devices = row["n_devices"]
    run.virtual_mesh = bool(row.get("virtual", default_virtual))
    if isinstance(row.get("ring_step_s"), (int, float)):
        run.mesh_ring_step_s = float(row["ring_step_s"])
    if isinstance(row.get("overlap_efficiency"), (int, float)):
        run.mesh_overlap_efficiency = float(row["overlap_efficiency"])


def ingest_bench(path: str, run_id: Optional[str] = None) -> PerfRun:
    """One BENCH artifact (wrapper or bare line) -> PerfRun.  Never
    raises on malformed content: a truncated file becomes a failed run
    whose error records the parse failure (the r03 lesson — a bench
    that can eat the scoreboard is itself a defect applies doubly to
    the tool reading the scoreboard)."""
    try:
        with open(path) as f:
            raw = f.read()
    except OSError as e:
        raw = ""
        doc: Optional[Dict[str, Any]] = None
        parse_error = f"{type(e).__name__}: {e}"
    else:
        try:
            doc = json.loads(raw)
            parse_error = None
        except json.JSONDecodeError as e:
            doc = None
            parse_error = f"unparseable JSON: {e}"

    if doc is None:
        run = PerfRun(
            run_id=run_id or _run_id_for(path, None, "bench"),
            kind="bench",
            source=path,
            failure_class=classify(None, None, raw),
            ok=False,
            error=parse_error,
        )
        return run

    if "parsed" in doc or "tail" in doc:  # driver wrapper
        n = doc.get("n") if isinstance(doc.get("n"), int) else None
        rc = doc.get("rc") if isinstance(doc.get("rc"), int) else None
        parsed = doc.get("parsed") if isinstance(doc.get("parsed"), dict) else None
        tail = str(doc.get("tail") or "")
        fc = classify(parsed, rc, tail)
        run = PerfRun(
            run_id=run_id or _run_id_for(path, n, "bench"),
            kind="bench",
            source=path,
            failure_class=fc,
            ok=fc == "ok",
            n=n,
            rc=rc,
        )
        if parsed is not None:
            _bench_run_from_parsed(run, parsed)
        if run.error is None and fc != "ok":
            run.error = _evidence_line(tail)
        return run

    # bare bench JSON line (tunnel_wait artifact or a raw bench capture)
    rc = doc.get("bench_rc") if isinstance(doc.get("bench_rc"), int) else None
    fc = classify(doc, rc, "")
    run = PerfRun(
        run_id=run_id or _run_id_for(path, None, "bench"),
        kind="bench",
        source=path,
        failure_class=fc,
        ok=fc == "ok",
        rc=rc,
    )
    return _bench_run_from_parsed(run, doc)


def _last_json_line(text: str) -> Optional[Dict[str, Any]]:
    for line in reversed([l for l in text.splitlines() if l.startswith("{")]):
        try:
            doc = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(doc, dict):
            return doc
    return None


def ingest_multichip(path: str, run_id: Optional[str] = None) -> PerfRun:
    """One MULTICHIP dryrun wrapper -> PerfRun.  New dryruns print a
    JSON line with cells_per_sec_per_chip into the tail; old ones carry
    only the human OK line, which still classifies."""
    try:
        with open(path) as f:
            raw = f.read()
        doc = json.loads(raw)
    except (OSError, json.JSONDecodeError) as e:
        return PerfRun(
            run_id=run_id or _run_id_for(path, None, "multichip"),
            kind="multichip",
            source=path,
            failure_class="engine",
            ok=False,
            error=f"unparseable artifact: {e}",
        )
    rc = doc.get("rc") if isinstance(doc.get("rc"), int) else None
    tail = str(doc.get("tail") or "")
    ok = bool(doc.get("ok"))
    fc = "ok" if ok else classify(None, rc, tail)
    run = PerfRun(
        run_id=run_id or _run_id_for(path, None, "multichip"),
        kind="multichip",
        source=path,
        failure_class=fc,
        ok=ok,
        rc=rc,
        n_devices=doc.get("n_devices")
        if isinstance(doc.get("n_devices"), int)
        else None,
    )
    line = _last_json_line(tail)
    if line and isinstance(
        line.get("cells_per_sec_per_chip"), (int, float)
    ):
        # same schema, same parser as a bench detail.mesh row — the
        # dryrun emits one JSON line per device count in that shape
        _ingest_mesh_row(run, line)
    if not ok and run.error is None:
        run.error = _evidence_line(tail)
    return run


class Ledger:
    """The ordered run history the sentinel and report operate on."""

    def __init__(self, runs: Iterable[PerfRun] = ()):
        self.runs: List[PerfRun] = sorted(runs, key=PerfRun.sort_key)

    def add(self, run: PerfRun) -> None:
        self.runs.append(run)
        self.runs.sort(key=PerfRun.sort_key)

    def bench_runs(self) -> List[PerfRun]:
        return [r for r in self.runs if r.kind == "bench"]

    def multichip_runs(self) -> List[PerfRun]:
        return [r for r in self.runs if r.kind == "multichip"]

    def ok_bench_runs(self) -> List[PerfRun]:
        return [r for r in self.bench_runs() if r.failure_class == "ok"]

    def latest_bench(self) -> Optional[PerfRun]:
        runs = self.bench_runs()
        return runs[-1] if runs else None

    def latest_multichip(self) -> Optional[PerfRun]:
        runs = self.multichip_runs()
        return runs[-1] if runs else None

    def counts_by_class(self) -> Dict[str, int]:
        out = {c: 0 for c in FAILURE_CLASSES}
        for r in self.runs:
            out[r.failure_class] += 1
        return out

    def to_dict(self) -> Dict[str, Any]:
        return {"runs": [r.to_dict() for r in self.runs]}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Ledger":
        return cls(PerfRun.from_dict(r) for r in d.get("runs", []))


def load_ledger(
    root: str = ".",
    bench_glob: str = "BENCH_r*.json",
    multichip_glob: str = "MULTICHIP_r*.json",
    extra_bench: Iterable[str] = (),
) -> Ledger:
    """Glob the round artifacts under `root` into a Ledger."""
    ledger = Ledger()
    for path in sorted(_glob.glob(os.path.join(root, bench_glob))):
        ledger.add(ingest_bench(path))
    for path in sorted(_glob.glob(os.path.join(root, multichip_glob))):
        ledger.add(ingest_multichip(path))
    for path in extra_bench:
        ledger.add(ingest_bench(path))
    return ledger
