"""Trend report + Prometheus publication (`cyclonus-tpu perf report`).

The markdown report is the human face of the ledger: one row per run
with rate / warmup / failure class, the per-chip scaling evidence, and
the cold-start forensics for every infra flake.  `publish()` mirrors
the same numbers into `cyclonus_tpu_perf_*` gauges on the process-wide
telemetry registry, so any process already serving `--metrics-port`
(probe, generate, worker — telemetry/server.py) exposes the perf
history to a scraper next to the live engine metrics.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional

from ..telemetry.metrics import REGISTRY
from .ledger import Ledger
from .schema import FAILURE_CLASSES, INFRA_CLASSES
from .sentinel import GateResult

# --- the cyclonus_tpu_perf_* instruments --------------------------------
# Declared at import like telemetry/instruments.py, so a scrape of a
# fresh process already shows the schema; per-run series appear when
# publish() runs.

PERF_CELLS_PER_SEC = REGISTRY.gauge(
    "cyclonus_tpu_perf_cells_per_sec",
    "Ledger: headline synchronous rate per benchmark run.",
    labelnames=("run",),
)
PERF_WARMUP_SECONDS = REGISTRY.gauge(
    "cyclonus_tpu_perf_warmup_seconds",
    "Ledger: warmup wall-clock per benchmark run.",
    labelnames=("run",),
)
PERF_PHASE_SECONDS = REGISTRY.gauge(
    "cyclonus_tpu_perf_phase_seconds",
    "Ledger: normalized per-phase wall-clock per benchmark run.",
    labelnames=("run", "phase"),
)
PERF_CELLS_PER_SEC_PER_CHIP = REGISTRY.gauge(
    "cyclonus_tpu_perf_cells_per_sec_per_chip",
    "Ledger: per-chip rate of runs that recorded one (virtual=1 marks "
    "CPU-mesh rates, which are shape evidence, not speedup).",
    labelnames=("run", "virtual"),
)
PERF_CLASS_RATIO = REGISTRY.gauge(
    "cyclonus_tpu_perf_class_compression_ratio",
    "Ledger: equivalence-class compression ratio (pods/classes) of runs "
    "that recorded one.",
    labelnames=("run",),
)
PERF_ROOFLINE_EFFICIENCY = REGISTRY.gauge(
    "cyclonus_tpu_perf_roofline_efficiency",
    "Ledger: measured eval vs the analytic roofline for its shapes "
    "(detail.roofline.efficiency_vs_roofline); gated >= 0.7 on "
    "pack-bearing runs.",
    labelnames=("run",),
)
PERF_AOT_ADOPTED = REGISTRY.gauge(
    "cyclonus_tpu_perf_aot_adopted",
    "Ledger: serialized AOT executables a run adopted at cold start "
    "(detail.cold_start.aot_cache.adopted); > 0 marks the run "
    "cache-bearing, which hard-gates its warmup_s.",
    labelnames=("run",),
)
PERF_CHAOS_TTFV = REGISTRY.gauge(
    "cyclonus_tpu_perf_chaos_ttfv_seconds",
    "Ledger: time-to-first-verdict of the chaos kill/restart leg "
    "(detail.chaos.ttfv_s; hard-bounded inside the bench leg).",
    labelnames=("run",),
)
PERF_RUNS = REGISTRY.gauge(
    "cyclonus_tpu_perf_runs",
    "Ledger: ingested runs by failure class.",
    labelnames=("failure_class",),
)
PERF_BEST_CELLS_PER_SEC = REGISTRY.gauge(
    "cyclonus_tpu_perf_best_cells_per_sec",
    "Ledger: best healthy synchronous rate across the history.",
)
PERF_GATE_STATUS = REGISTRY.gauge(
    "cyclonus_tpu_perf_gate_status",
    "Last regression-gate outcome: 0 pass/no-data, 1 engine "
    "regression, 2 infra flake.",
)


def publish(ledger: Ledger, result: Optional[GateResult] = None) -> None:
    """Mirror the ledger (and optionally a gate outcome) into the
    cyclonus_tpu_perf_* gauges."""
    best = 0.0
    for run in ledger.bench_runs():
        PERF_CELLS_PER_SEC.set(run.cells_per_sec, run=run.run_id)
        if run.warmup_s is not None:
            PERF_WARMUP_SECONDS.set(run.warmup_s, run=run.run_id)
        for phase, seconds in run.phases.items():
            PERF_PHASE_SECONDS.set(seconds, run=run.run_id, phase=phase)
        if run.class_compression_ratio is not None:
            PERF_CLASS_RATIO.set(
                run.class_compression_ratio, run=run.run_id
            )
        if run.roofline_efficiency is not None:
            PERF_ROOFLINE_EFFICIENCY.set(
                run.roofline_efficiency, run=run.run_id
            )
        if run.aot_adopted is not None:
            PERF_AOT_ADOPTED.set(run.aot_adopted, run=run.run_id)
        if run.chaos_ttfv_s is not None:
            PERF_CHAOS_TTFV.set(run.chaos_ttfv_s, run=run.run_id)
        if run.failure_class == "ok":
            best = max(best, run.cells_per_sec)
    for run in ledger.runs:
        if run.cells_per_sec_per_chip is not None:
            PERF_CELLS_PER_SEC_PER_CHIP.set(
                run.cells_per_sec_per_chip,
                run=run.run_id,
                virtual="1" if run.virtual_mesh else "0",
            )
    for cls, count in ledger.counts_by_class().items():
        PERF_RUNS.set(count, failure_class=cls)
    PERF_BEST_CELLS_PER_SEC.set(best)
    if result is not None:
        PERF_GATE_STATUS.set(float(result.exit_code))


def trend(ledger: Ledger, result: Optional[GateResult] = None) -> Dict[str, Any]:
    """The JSON report: per-run rows + aggregates (+ gate outcome)."""
    ok_runs = ledger.ok_bench_runs()
    doc: Dict[str, Any] = {
        "runs": [r.to_dict() for r in ledger.runs],
        "by_class": ledger.counts_by_class(),
        "best_cells_per_sec": max(
            (r.cells_per_sec for r in ok_runs), default=0.0
        ),
        "best_warmup_s": min(
            (r.warmup_s for r in ok_runs if r.warmup_s is not None),
            default=None,
        ),
        "healthy_trajectory": [
            {"run": r.run_id, "cells_per_sec": r.cells_per_sec}
            for r in ok_runs
        ],
        "class_compression": [
            {"run": r.run_id, "ratio": r.class_compression_ratio}
            for r in ledger.bench_runs()
            if r.class_compression_ratio is not None
        ],
        "roofline_efficiency": [
            {
                "run": r.run_id,
                "efficiency": r.roofline_efficiency,
                "pack": r.pack_active,
                "tile": r.pack_tile,
            }
            for r in ledger.bench_runs()
            if r.roofline_efficiency is not None
        ],
    }
    if result is not None:
        doc["gate"] = result.to_dict()
    return doc


def _human_rate(v: float) -> str:
    if v >= 1e9:
        return f"{v / 1e9:.1f}B"
    if v >= 1e6:
        return f"{v / 1e6:.1f}M"
    return f"{v:g}"


def render_markdown(
    ledger: Ledger, result: Optional[GateResult] = None
) -> str:
    """The human trend report."""
    lines = [
        "# Perf observatory",
        "",
        "| run | kind | class | cells/s | warmup_s | per-chip | cls-ratio | roofline | note |",
        "|---|---|---|---|---|---|---|---|---|",
    ]
    for r in ledger.runs:
        per_chip = (
            f"{_human_rate(r.cells_per_sec_per_chip)}"
            + (" (virtual)" if r.virtual_mesh else "")
            if r.cells_per_sec_per_chip is not None
            else "-"
        )
        ratio = (
            f"{r.class_compression_ratio:g}x"
            if r.class_compression_ratio is not None
            else "-"
        )
        eff = (
            f"{r.roofline_efficiency:g}"
            + (" (packed)" if r.pack_active else "")
            if r.roofline_efficiency is not None
            else "-"
        )
        note = ""
        if r.failure_class != "ok":
            note = (r.error or "")[:80]
        lines.append(
            f"| {r.run_id} | {r.kind} | {r.failure_class} "
            f"| {_human_rate(r.cells_per_sec) if r.cells_per_sec else '-'} "
            f"| {r.warmup_s if r.warmup_s is not None else '-'}"
            f"{' (aot)' if r.aot_adopted else ''} "
            f"| {per_chip} | {ratio} | {eff} | {note} |"
        )
    by_class = ledger.counts_by_class()
    infra = sum(by_class[c] for c in INFRA_CLASSES)
    lines += [
        "",
        f"- runs: {len(ledger.runs)} "
        f"({', '.join(f'{c}={by_class[c]}' for c in FAILURE_CLASSES if by_class[c])})",
        f"- infra flakes excluded from the trajectory: {infra}",
    ]
    ok_runs = ledger.ok_bench_runs()
    if ok_runs:
        best = max(ok_runs, key=lambda r: r.cells_per_sec)
        lines.append(
            f"- best healthy rate: {_human_rate(best.cells_per_sec)} "
            f"cells/s ({best.run_id})"
        )
        warm = [r for r in ok_runs if r.warmup_s is not None]
        if warm:
            bw = min(warm, key=lambda r: r.warmup_s)
            lines.append(
                f"- best warmup: {bw.warmup_s}s ({bw.run_id})"
            )
        ttfv = [r for r in ok_runs if r.chaos_ttfv_s is not None]
        if ttfv:
            bt = min(ttfv, key=lambda r: r.chaos_ttfv_s)
            lines.append(
                f"- best chaos restart time-to-first-verdict: "
                f"{bt.chaos_ttfv_s}s ({bt.run_id})"
            )
    if result is not None:
        lines += ["", "## Gate", "", "```", result.report(), "```"]
    return "\n".join(lines) + "\n"


def render(
    ledger: Ledger,
    fmt: str = "markdown",
    result: Optional[GateResult] = None,
) -> str:
    if fmt == "json":
        return json.dumps(trend(ledger, result), indent=2) + "\n"
    if fmt == "prometheus":
        publish(ledger, result)
        return REGISTRY.render_prometheus()
    return render_markdown(ledger, result)
