"""Perf observatory: the longitudinal side of the telemetry subsystem.

Rounds 1-5 left the perf trajectory (6.6B -> 132.7B cells/s, warmup
65s -> 7.2s) in write-only BENCH_r0*.json blobs, and the two rounds that
failed (r03/r04) failed on TPU backend/tunnel init — indistinguishable,
to any tool, from an engine regression.  This package turns those blobs
into a queryable history with gates:

  schema.py    one normalized run record (PerfRun): run id, per-phase
               wall-clock, warmup breakdown, cells/s, cells/s-per-chip,
               telemetry counters, and a failure_class
               (backend_init | tunnel | watchdog_stall | engine | ok)
  ledger.py    ingests BENCH_r*.json / MULTICHIP_r*.json (and bare
               bench JSON lines from tools/tunnel_wait.py artifacts)
               into a Ledger, classifying every failure from the
               evidence the artifact carries — truncated files and
               parsed-null rc=124 wrappers included
  sentinel.py  the noise-aware regression gate (`cyclonus-tpu perf
               gate`, `make perf-gate`): min-of-N baselines over prior
               healthy runs, per-phase bounds, hard gates on
               cells_per_sec / warmup_s / multichip scaling efficiency;
               infra flakes (backend_init/tunnel) gate SEPARATELY from
               engine regressions (distinct exit code), so a dead
               tunnel can never read as a kernel regression again
  report.py    markdown/JSON trend report (`cyclonus-tpu perf report`)
               and the cyclonus_tpu_perf_* Prometheus gauges published
               through the existing telemetry registry/metrics server

Everything here is host-side stdlib: no jax import, no device contact —
the gate must run on a machine whose TPU tunnel is dead, because that is
exactly the situation it exists to diagnose.
"""

from __future__ import annotations

from .ledger import Ledger, classify, ingest_bench, ingest_multichip, load_ledger
from .schema import FAILURE_CLASSES, INFRA_CLASSES, PerfRun
from .sentinel import GateResult, gate

__all__ = [
    "FAILURE_CLASSES",
    "GateResult",
    "INFRA_CLASSES",
    "Ledger",
    "PerfRun",
    "classify",
    "gate",
    "ingest_bench",
    "ingest_multichip",
    "load_ledger",
]
