import sys

from .cli import main

if __name__ == "__main__":
    # the return value IS the process exit code — the perf gate (and
    # any CI caller of `python -m cyclonus_tpu`) depends on nonzero
    # propagating, exactly like the `cyclonus-tpu` console script
    sys.exit(main())
