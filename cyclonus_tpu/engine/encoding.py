"""Host-side tensor compiler: matcher IR + cluster model -> dense numpy
arrays ready for the TPU kernels.

Encoding scheme (see SURVEY.md section 7 step 3):
  * label vocabulary: every distinct (key, value) pair appearing on any pod,
    namespace, or selector gets an int id; every distinct key gets a key id.
  * pods: (namespace id, padded kv-id list, padded key-id list, IPv4 uint32);
    namespaces: (padded kv-id list, padded key-id list).
  * selectors: deduped; matchLabels as padded required-kv ids, up to E
    matchExpressions each (op, key id, padded value-kv ids).
  * targets: (namespace id, selector id) per direction.
  * peers: flat arrays with a target id and a kind code
    (ALL / ALL_PORTS / IP / POD); pod peers carry namespace-matcher and
    pod-matcher codes; ip peers carry premasked (base, mask) plus excepts.
  * port specs: per peer, up to I single items (nil/int/named x protocol)
    and R ranges.

Padding is provably neutral: padded kv ids are -1 (never equal to a real
id), padded expressions are op NONE (always true), padded peers belong to
target -1 (one-hot row of zeros), padded except-blocks carry valid=False.

Ragged semantics warning: everything here must mirror the scalar oracle in
cyclonus_tpu.matcher exactly — any divergence is caught by the parity tests
(tests/test_engine_parity.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..kube.ipaddr import cidr_to_base_and_prefix, ip_to_uint32
from ..utils import contracts
from ..kube.netpol import (
    OP_DOES_NOT_EXIST,
    OP_EXISTS,
    OP_IN,
    OP_NOT_IN,
    LabelSelector,
)
from ..kube.labels import serialize_label_selector
from ..matcher.core import (
    AllNamespaceMatcher,
    AllPeersMatcher,
    AllPodMatcher,
    AllPortMatcher,
    ExactNamespaceMatcher,
    IPPeerMatcher,
    LabelSelectorNamespaceMatcher,
    LabelSelectorPodMatcher,
    PodPeerMatcher,
    Policy,
    PortsForAllPeersMatcher,
    SpecificPortMatcher,
)

# selector expression opcodes
EXP_NONE = 0
EXP_IN = 1
EXP_NOT_IN = 2
EXP_EXISTS = 3
EXP_DOES_NOT_EXIST = 4

_OP_CODES = {
    OP_IN: EXP_IN,
    OP_NOT_IN: EXP_NOT_IN,
    OP_EXISTS: EXP_EXISTS,
    OP_DOES_NOT_EXIST: EXP_DOES_NOT_EXIST,
}

# peer kinds
PEER_ALL = 0  # AllPeersMatcher: everything
PEER_ALL_PORTS = 1  # PortsForAllPeersMatcher: any peer, port-matched
PEER_IP = 2  # IPPeerMatcher
PEER_POD = 3  # PodPeerMatcher

# namespace-matcher kinds (within a pod peer)
NS_EXACT = 0
NS_SELECTOR = 1
NS_ALL = 2

# pod-matcher kinds
POD_ALL = 0
POD_SELECTOR = 1

# port item kinds
PORT_NIL = 0  # protocol only
PORT_INT = 1
PORT_NAMED = 2

# precedence-tier verdict codes (int8 slab; docs/DESIGN.md "Precedence
# tiers").  0 is the PAD action: a padded tier rule matches nothing.
TIER_ACT_NONE = 0
TIER_ACT_ALLOW = 1
TIER_ACT_DENY = 2
TIER_ACT_PASS = 3

# tier ids within the shared slab
TIER_ANP = 0
TIER_BANP = 1

#: "no matching rule" priority-key sentinel: every real key is
#: rank * 4 + action < 2^30 (ranks are slab positions, actions 1-3)
TIER_KEY_NONE = 1 << 30

# --- bit-packed match slabs (docs/DESIGN.md "Bit-packed kernel") ----------
#
# The verdict contraction is pure boolean: any_allow = OR_t (tmatch[t] AND
# tallow[t]).  Packing the target axis 32-per-int32-word turns that OR of
# T bools into an OR of ceil(T/32) word AND-OR steps — a 32x cut of the
# contraction depth every evaluator shares (tiled bodies, the ring
# bundles, the packed Pallas kernel).  int32 is the one packed dtype:
# it is what api._pack_tensors ships, what Mosaic handles natively, and
# the word sum below never carries across bit lanes, so the sign bit is
# just bit 31.  The numpy packer here and the jnp twin
# (kernel.pack_bool_words_jnp) are pinned bit-identical by
# tests/test_engine_packed.py.

#: bits per packed word — the 32-per-word layout every packed slab uses
PACK_BITS = 32


def packed_words(n: int) -> int:
    """Words needed for `n` packed bits (>= 1): THE ceil-div round-up
    shapelint SC004 discharges for packed-word axes, factored out like
    pallas_kernel.lane_round_up so the 32-per-word arithmetic has one
    formula."""
    return -(-max(int(n), 1) // PACK_BITS)


def pack_bool_words(a: np.ndarray, axis: int = 0) -> np.ndarray:
    """Pack a bool array 32-per-word along `axis` into int32 words.

    Bit b of word w holds element w * 32 + b (little-endian within the
    word); the trailing word zero-pads.  Word values are built as a sum
    of disjoint shifted bits, which equals the bitwise OR exactly (no
    carries), including bit 31 riding the int32 sign."""
    a = np.moveaxis(np.asarray(a, dtype=bool), axis, 0)
    t = a.shape[0]
    w = packed_words(t)
    total = w * PACK_BITS  # tile: 32 — the 32-per-word round-up, SC004-proved
    pad = total - t
    if pad:
        a = np.concatenate(
            [a, np.zeros((pad,) + a.shape[1:], dtype=bool)], axis=0
        )
    bits = a.reshape((w, PACK_BITS) + a.shape[1:]).astype(np.uint32)
    shifts = (np.uint32(1) << np.arange(PACK_BITS, dtype=np.uint32)).reshape(
        (1, PACK_BITS) + (1,) * (a.ndim - 1)
    )
    words = (bits * shifts).sum(axis=1, dtype=np.uint32).view(np.int32)
    return np.moveaxis(words, 0, axis)


def pack_enabled(mode: Optional[str] = None) -> bool:
    """Resolve the CYCLONUS_PACK kill switch: "0" disables the packed
    path everywhere (the pre-PR representation, bit-identical by the
    packed parity suite); "1"/"auto" (default) enable it.  Resolved
    EAGERLY at public entry points and passed as a static argument —
    never read inside a traced function (the jit caches key on shapes
    plus statics, so an env flip after tracing must retrace, not be
    silently ignored; same discipline as CYCLONUS_PALLAS_DTYPE)."""
    import os

    if mode is None:
        mode = os.environ.get("CYCLONUS_PACK", "auto")
    mode = str(mode).lower()
    if mode not in ("auto", "0", "1"):
        raise ValueError(
            f"CYCLONUS_PACK must be auto, 0, or 1, got {mode!r}"
        )
    return mode != "0"

# protocols: TCP/UDP/SCTP preseeded; unknown protocol strings appearing in
# policies get fresh ids at encode time so that equal strings still match
# (the oracle compares protocol strings for equality — matcher/core.py).


@dataclass
class _Vocab:
    kv: Dict[Tuple[str, str], int] = field(default_factory=dict)
    key: Dict[str, int] = field(default_factory=dict)
    ns: Dict[str, int] = field(default_factory=dict)
    port_name: Dict[str, int] = field(default_factory=dict)
    proto: Dict[str, int] = field(
        default_factory=lambda: {"TCP": 0, "UDP": 1, "SCTP": 2}
    )

    def kv_id(self, k: str, v: str) -> int:
        return self.kv.setdefault((k, v), len(self.kv))

    def key_id(self, k: str) -> int:
        return self.key.setdefault(k, len(self.key))

    def ns_id(self, ns: str) -> int:
        return self.ns.setdefault(ns, len(self.ns))

    def port_name_id(self, name: str) -> int:
        if name == "":
            return -1
        return self.port_name.setdefault(name, len(self.port_name))

    def proto_id(self, protocol: str) -> int:
        return self.proto.setdefault(protocol, len(self.proto))


@contracts.checked
@dataclass
class ClusterEncoding:
    """Tensorized cluster: one row per pod, one row per namespace.

    Tensor contracts (tools/shapelint.py + utils/contracts.py; symbol
    table in docs/DESIGN.md "Tensor contracts"): N pods, M namespaces,
    L/Lns label pad widths.  Validated on construction under
    CYCLONUS_SHAPE_CHECK=1."""

    vocab: _Vocab
    pod_keys: List[str]  # "ns/name" in row order
    pod_ns_id: np.ndarray = contracts.tensor("(N,) int32")
    pod_kv: np.ndarray = contracts.tensor("(N, L) int32", sentinel="-1=pad")
    pod_key: np.ndarray = contracts.tensor("(N, L) int32", sentinel="-1=pad")
    # a parse-failure row holds uint32 0 — a REAL address (0.0.0.0) — so
    # the bool validity column, not the 0, is the ground truth: every
    # comparison against pod_ip must consult pod_ip_valid (SC003)
    pod_ip: np.ndarray = contracts.tensor(
        "(N,) uint32", sentinel="0=invalid", mask="pod_ip_valid"
    )
    pod_ip_valid: np.ndarray = contracts.tensor("(N,) bool")
    pod_ips: List[str]  # raw strings, for host-side v6 fallback
    ns_kv: np.ndarray = contracts.tensor("(M, Lns) int32", sentinel="-1=pad")
    ns_key: np.ndarray = contracts.tensor("(M, Lns) int32", sentinel="-1=pad")

    @property
    def n_pods(self) -> int:
        return len(self.pod_keys)


def _encode_label_rows(
    label_maps: Sequence[Dict[str, str]], vocab: _Vocab
) -> Tuple[np.ndarray, np.ndarray]:
    """Vocab-encode per-row label maps to padded id matrices.

    Vectorized for large clusters: per-row dict walks produce flat
    (row, key, value) triples, the vocab lookup runs once per DISTINCT
    pair/key (label cardinality is tiny next to pod count), and the
    padded matrices fill with one scatter.  Vocab id assignment order is
    identical to the scalar form (first appearance in row-major sorted
    order), so selector tables encoded earlier against the same vocab
    stay consistent."""
    n = len(label_maps)
    # Distinct-map dedup: clusters repeat a small set of label maps
    # across huge pod counts (replicas share a template), so encode each
    # DISTINCT map once and scatter by row index.  The cache key is the
    # map's insertion-order items — equal maps built in different orders
    # just dedup less, never wrongly merge.  Vocab id assignment order
    # is unchanged: a repeated map introduces no new pair on later
    # appearances, so first-appearance order over distinct maps equals
    # first-appearance order over all rows.
    row_of = np.empty(n, dtype=np.int32)
    distinct_index: Dict[tuple, int] = {}
    label_maps_d: List[Dict[str, str]] = []
    for i, m in enumerate(label_maps):
        cache_key = tuple(m.items())
        rid = distinct_index.get(cache_key)
        if rid is None:
            rid = distinct_index[cache_key] = len(label_maps_d)
            label_maps_d.append(m)
        row_of[i] = rid
    if len(label_maps_d) < n:
        kv_d, key_d = _encode_label_rows(label_maps_d, vocab)
        return kv_d[row_of], key_d[row_of]

    max_l = max((len(m) for m in label_maps), default=0)
    max_l = max(max_l, 1)
    kv = np.full((n, max_l), -1, dtype=np.int32)
    key = np.full((n, max_l), -1, dtype=np.int32)
    rows, cols, ks, vs = [], [], [], []
    for i, m in enumerate(label_maps):
        for j, (k, v) in enumerate(sorted(m.items())):
            rows.append(i)
            cols.append(j)
            ks.append(k)
            vs.append(v)
    if not rows:
        return kv, key
    # id-assign in first-appearance order over the flat stream, visiting
    # the dict only once per distinct pair/key
    kv_ids = np.empty(len(rows), dtype=np.int32)
    key_ids = np.empty(len(rows), dtype=np.int32)
    kv_cache: Dict[Tuple[str, str], int] = {}
    key_cache: Dict[str, int] = {}
    for idx, (k, v) in enumerate(zip(ks, vs)):
        pair = (k, v)
        kv_cached = kv_cache.get(pair)
        if kv_cached is None:
            kv_cached = kv_cache[pair] = vocab.kv_id(k, v)
        kv_ids[idx] = kv_cached
        key_cached = key_cache.get(k)
        if key_cached is None:
            key_cached = key_cache[k] = vocab.key_id(k)
        key_ids[idx] = key_cached
    kv[rows, cols] = kv_ids
    key[rows, cols] = key_ids
    return kv, key


_STRICT_IPV4_LINES = None  # compiled lazily (module import stays light)


def _encode_pod_ips(ips: Sequence[str]) -> Tuple[np.ndarray, np.ndarray]:
    """(pod_ip uint32 [N], pod_ip_valid bool [N]) for all pods at once.

    Contract (ClusterEncoding.pod_ip): a parse failure fills uint32 0 —
    a REAL address (0.0.0.0) — with the bool column as ground truth, so
    every consumer comparison must be pod_ip_valid-guarded (shapelint
    SC003 enforces this wherever the mask-declared field is compared).

    Bulk fast path: ONE multiline regex pass over the joined IP strings
    (the strict octet grammar — exactly what _fast_ipv4_to_uint32
    accepts: no leading zeros, no signs/whitespace, 0-255) and one numpy
    combine.  Any line that doesn't match breaks the count, and the
    whole batch falls back to the per-item path — mixed/IPv6 clusters
    keep exact semantics, all-IPv4 clusters (the big ones) skip ~4us of
    python per pod."""
    global _STRICT_IPV4_LINES
    if not ips:
        return np.zeros((0,), np.uint32), np.zeros((0,), bool)
    if _STRICT_IPV4_LINES is None:
        import re

        octet = r"(25[0-5]|2[0-4][0-9]|1[0-9][0-9]|[1-9][0-9]|[0-9])"
        _STRICT_IPV4_LINES = re.compile(
            rf"(?m)^{octet}\.{octet}\.{octet}\.{octet}$"
        )
    if not any("\n" in ip for ip in ips):
        matches = _STRICT_IPV4_LINES.findall("\n".join(ips))
        if len(matches) == len(ips):
            octets = np.array(matches, dtype=np.uint32)  # [N, 4]
            ip_int = (
                (octets[:, 0] << 24)
                | (octets[:, 1] << 16)
                | (octets[:, 2] << 8)
                | octets[:, 3]
            )
            return ip_int.astype(np.uint32), np.ones(len(ips), dtype=bool)
    ip_ints = [_fast_ipv4_to_uint32(ip) for ip in ips]
    return (
        np.array([i or 0 for i in ip_ints], dtype=np.uint32),
        np.array([i is not None for i in ip_ints], dtype=bool),
    )


def _fast_ipv4_to_uint32(ip: str) -> Optional[int]:
    """Dotted-quad fast path for the per-pod encode loop (ipaddress.
    ip_address costs ~4us/call, dominating 100k+-pod encodes); anything
    unusual falls back to the oracle-faithful ip_to_uint32."""
    parts = ip.split(".")
    if len(parts) != 4:
        return ip_to_uint32(ip)
    out = 0
    for x in parts:
        # reject forms ipaddress rejects: empty/oversize octets, signs,
        # whitespace, non-ASCII digits (isdigit alone accepts those and
        # int() converts them), leading zeros, out-of-range values
        n = len(x)
        if (
            n == 0
            or n > 3
            or not x.isascii()
            or not x.isdigit()
            or (n > 1 and x[0] == "0")
        ):
            return ip_to_uint32(ip)
        v = int(x)
        if v > 255:
            return ip_to_uint32(ip)
        out = (out << 8) | v
    return out


def encode_cluster(
    pods: Sequence[Tuple[str, str, Dict[str, str], str]],
    namespaces: Dict[str, Dict[str, str]],
    vocab: Optional[_Vocab] = None,
) -> ClusterEncoding:
    """pods: (namespace, name, labels, ip) per pod.
    namespaces: ns -> labels.

    The namespace-label rows are indexed BY VOCAB NS ID (the vocab may
    already hold ids for policy-target namespaces, and pods may live in
    namespaces absent from the dict) — a namespace with no labels entry gets
    an all-pad row, matching the oracle's empty-label semantics for unknown
    namespaces."""
    vocab = vocab or _Vocab()
    for ns in namespaces:
        vocab.ns_id(ns)
    for p in pods:
        vocab.ns_id(p[0])
    n_ns = len(vocab.ns)
    label_rows: List[Dict[str, str]] = [{} for _ in range(n_ns)]
    for ns, labels in namespaces.items():
        label_rows[vocab.ns_id(ns)] = labels
    ns_kv, ns_key = _encode_label_rows(label_rows, vocab)

    pod_ns_id = np.array(
        [vocab.ns_id(p[0]) for p in pods], dtype=np.int32
    ) if pods else np.zeros((0,), dtype=np.int32)
    pod_kv, pod_key = _encode_label_rows([p[2] for p in pods], vocab)
    ips = [p[3] for p in pods]
    pod_ip, pod_ip_valid = _encode_pod_ips(ips)
    return ClusterEncoding(
        vocab=vocab,
        pod_keys=[f"{p[0]}/{p[1]}" for p in pods],
        pod_ns_id=pod_ns_id,
        pod_kv=pod_kv,
        pod_key=pod_key,
        pod_ip=pod_ip,
        pod_ip_valid=pod_ip_valid,
        pod_ips=list(ips),
        ns_kv=ns_kv,
        ns_key=ns_key,
    )


@dataclass
class _SelectorTable:
    """Deduped selectors encoded as fixed-width arrays."""

    index: Dict[str, int] = field(default_factory=dict)
    selectors: List[LabelSelector] = field(default_factory=list)
    # object-level memo in front of the serialize-keyed dedup: selectors
    # are frozen/hashable, and serialize_label_selector (json.dumps) is
    # the encode hot spot at 10k+ policies.  Memo and serialization read
    # the same fields (serialization preserves expression order, as does
    # dataclass equality), so the memo can never merge selectors the
    # index would keep distinct.
    _memo: Dict[LabelSelector, int] = field(default_factory=dict)

    def sel_id(self, selector: LabelSelector) -> int:
        sid = self._memo.get(selector)
        if sid is not None:
            return sid
        key = serialize_label_selector(selector)
        if key not in self.index:
            self.index[key] = len(self.selectors)
            self.selectors.append(selector)
        sid = self.index[key]
        self._memo[selector] = sid
        return sid

    def encode(self, vocab: _Vocab):
        n = len(self.selectors)
        max_r = max((len(s.match_labels_items) for s in self.selectors), default=0)
        max_e = max((len(s.match_expressions) for s in self.selectors), default=0)
        max_v = max(
            (
                len(e.values)
                for s in self.selectors
                for e in s.match_expressions
            ),
            default=0,
        )
        max_r, max_e, max_v = max(max_r, 1), max(max_e, 1), max(max_v, 1)
        req_kv = np.full((n, max_r), -1, dtype=np.int32)
        exp_op = np.full((n, max_e), EXP_NONE, dtype=np.int32)
        exp_key = np.full((n, max_e), -1, dtype=np.int32)
        exp_vals = np.full((n, max_e, max_v), -1, dtype=np.int32)
        for i, s in enumerate(self.selectors):
            for j, (k, v) in enumerate(s.match_labels_items):
                req_kv[i, j] = vocab.kv_id(k, v)
            for j, e in enumerate(s.match_expressions):
                exp_op[i, j] = _OP_CODES[e.operator]
                exp_key[i, j] = vocab.key_id(e.key)
                for vi, v in enumerate(e.values):
                    exp_vals[i, j, vi] = vocab.kv_id(e.key, v)
        return req_kv, exp_op, exp_key, exp_vals


@dataclass
class _PortSpecBuilder:
    """Per-peer port spec rows."""

    all_flag: List[bool] = field(default_factory=list)
    items: List[List[Tuple[int, int, int, int]]] = field(default_factory=list)
    # item: (kind, port_int, name_id, proto_id)
    ranges: List[List[Tuple[int, int, int]]] = field(default_factory=list)
    # range: (from, to, proto_id)

    def add(self, port_matcher, vocab: _Vocab) -> None:
        if isinstance(port_matcher, AllPortMatcher):
            self.all_flag.append(True)
            self.items.append([])
            self.ranges.append([])
            return
        if not isinstance(port_matcher, SpecificPortMatcher):
            raise TypeError(f"invalid PortMatcher type {type(port_matcher)}")
        items = []
        for pp in port_matcher.ports:
            pid = vocab.proto_id(pp.protocol)
            if pp.port is None:
                items.append((PORT_NIL, 0, -1, pid))
            elif pp.port.is_int:
                items.append((PORT_INT, pp.port.int_value, -1, pid))
            else:
                items.append(
                    (PORT_NAMED, 0, vocab.port_name_id(pp.port.str_value), pid)
                )
        ranges = [
            (r.from_port, r.to_port, vocab.proto_id(r.protocol))
            for r in port_matcher.port_ranges
        ]
        self.all_flag.append(False)
        self.items.append(items)
        self.ranges.append(ranges)

    def encode(self):
        n = len(self.all_flag)
        max_i = max((len(x) for x in self.items), default=0)
        max_r = max((len(x) for x in self.ranges), default=0)
        max_i, max_r = max(max_i, 1), max(max_r, 1)
        item_kind = np.full((n, max_i), -1, dtype=np.int32)  # -1 = pad, no match
        item_port = np.zeros((n, max_i), dtype=np.int32)
        item_name = np.full((n, max_i), -2, dtype=np.int32)  # -2 never equals -1
        item_proto = np.full((n, max_i), -2, dtype=np.int32)
        rng_from = np.zeros((n, max_r), dtype=np.int32)
        rng_to = np.full((n, max_r), -1, dtype=np.int32)  # empty range
        rng_proto = np.full((n, max_r), -2, dtype=np.int32)
        for i in range(n):
            for j, (kind, port, name, proto) in enumerate(self.items[i]):
                item_kind[i, j] = kind
                item_port[i, j] = port
                item_name[i, j] = name
                item_proto[i, j] = proto
            for j, (f, t, proto) in enumerate(self.ranges[i]):
                rng_from[i, j] = f
                rng_to[i, j] = t
                rng_proto[i, j] = proto
        return {
            "spec_all": np.array(self.all_flag, dtype=bool),
            "item_kind": item_kind,
            "item_port": item_port,
            "item_name": item_name,
            "item_proto": item_proto,
            "rng_from": rng_from,
            "rng_to": rng_to,
            "rng_proto": rng_proto,
        }


@contracts.checked
@dataclass
class _DirectionEncoding:
    """Targets + flattened peers for one direction (ingress or egress).

    Tensor contracts: T targets, P flat peers, X except-block pad width.
    Validated on construction under CYCLONUS_SHAPE_CHECK=1."""

    n_targets: int
    # -1: namespace unknown to cluster
    target_ns: np.ndarray = contracts.tensor("(T,) int32", sentinel="-1=pad")
    target_sel: np.ndarray = contracts.tensor("(T,) int32")  # selector id
    # peers, flat (pad peers belong to target -1: zero one-hot row):
    peer_target: np.ndarray = contracts.tensor("(P,) int32", sentinel="-1=pad")
    # peer's index WITHIN its target (rule provenance for the analysis
    # layer: flat row p is rule (peer_target[p], peer_rule_idx[p]) of
    # the sorted_targets() order)
    peer_rule_idx: np.ndarray = contracts.tensor("(P,) int32")
    peer_kind: np.ndarray = contracts.tensor("(P,) int32")
    peer_ns_kind: np.ndarray = contracts.tensor("(P,) int32")  # (pod peers)
    peer_ns_id: np.ndarray = contracts.tensor(
        "(P,) int32", sentinel="-1=pad"
    )  # (NS_EXACT)
    peer_ns_sel: np.ndarray = contracts.tensor(
        "(P,) int32", sentinel="-1=pad"
    )  # (NS_SELECTOR)
    peer_pod_kind: np.ndarray = contracts.tensor("(P,) int32")
    peer_pod_sel: np.ndarray = contracts.tensor("(P,) int32", sentinel="-1=pad")
    # ip peers (IPv4 in-kernel; v6 handled via host rows).  base/mask
    # rows are only meaningful where ip_is_v4 — non-v4 rows hold 0,
    # which as uint32 data would be 0.0.0.0/0 (match everything)
    ip_base: np.ndarray = contracts.tensor(
        "(P,) uint32", sentinel="0=inert", mask="ip_is_v4"
    )  # (pre-masked)
    ip_mask: np.ndarray = contracts.tensor(
        "(P,) uint32", sentinel="0=inert", mask="ip_is_v4"
    )
    ip_is_v4: np.ndarray = contracts.tensor("(P,) bool")
    ex_base: np.ndarray = contracts.tensor(
        "(P, X) uint32", sentinel="0=inert", mask="ex_valid"
    )
    ex_mask: np.ndarray = contracts.tensor(
        "(P, X) uint32", sentinel="0=inert", mask="ex_valid"
    )
    ex_valid: np.ndarray = contracts.tensor("(P, X) bool")
    host_ip_rows: List[Tuple[int, IPPeerMatcher]]  # v6 fallback: peer row -> matcher
    port_spec: Dict[str, np.ndarray]  # per-peer port spec arrays

    @property
    def n_peers(self) -> int:
        return len(self.peer_target)


def _mask_for_prefix(prefix: int) -> int:
    return 0 if prefix == 0 else (0xFFFFFFFF << (32 - prefix)) & 0xFFFFFFFF


def _encode_direction(
    targets, sel_table: _SelectorTable, vocab: _Vocab
) -> _DirectionEncoding:
    t_ns, t_sel = [], []
    p_target, p_rule_idx, p_kind = [], [], []
    p_ns_kind, p_ns_id, p_ns_sel = [], [], []
    p_pod_kind, p_pod_sel = [], []
    ip_rows: List[Tuple[int, int, bool]] = []  # (base, mask, is_v4)
    ex_rows: List[List[Tuple[int, int]]] = []
    host_ip_rows: List[Tuple[int, IPPeerMatcher]] = []
    specs = _PortSpecBuilder()

    for t_idx, target in enumerate(targets):
        # target namespace must match by name; namespaces not present in the
        # cluster can't match any pod, but we register them in the vocab so
        # equality against pod ns ids is well-defined either way.
        t_ns.append(vocab.ns_id(target.namespace))
        t_sel.append(sel_table.sel_id(target.pod_selector))
        for peer_idx, peer in enumerate(target.peers):
            p_target.append(t_idx)
            p_rule_idx.append(peer_idx)
            if isinstance(peer, AllPeersMatcher):
                p_kind.append(PEER_ALL)
                specs.add(AllPortMatcher(), vocab)
                p_ns_kind.append(NS_ALL)
                p_ns_id.append(-1)
                p_ns_sel.append(-1)
                p_pod_kind.append(POD_ALL)
                p_pod_sel.append(-1)
                ip_rows.append((0, 0, False))
                ex_rows.append([])
            elif isinstance(peer, PortsForAllPeersMatcher):
                p_kind.append(PEER_ALL_PORTS)
                specs.add(peer.port, vocab)
                p_ns_kind.append(NS_ALL)
                p_ns_id.append(-1)
                p_ns_sel.append(-1)
                p_pod_kind.append(POD_ALL)
                p_pod_sel.append(-1)
                ip_rows.append((0, 0, False))
                ex_rows.append([])
            elif isinstance(peer, IPPeerMatcher):
                p_kind.append(PEER_IP)
                specs.add(peer.port, vocab)
                p_ns_kind.append(NS_ALL)
                p_ns_id.append(-1)
                p_ns_sel.append(-1)
                p_pod_kind.append(POD_ALL)
                p_pod_sel.append(-1)
                bp = cidr_to_base_and_prefix(peer.ip_block.cidr)
                if bp is None:
                    # IPv6 CIDR: evaluate host-side (rare), kernel row inert
                    ip_rows.append((0, 0, False))
                    ex_rows.append([])
                    host_ip_rows.append((len(p_target) - 1, peer))
                else:
                    base, prefix = bp
                    mask = _mask_for_prefix(prefix)
                    ip_rows.append((base & mask, mask, True))
                    exs = []
                    v6_except = False
                    for ex in peer.ip_block.except_:
                        ebp = cidr_to_base_and_prefix(ex)
                        if ebp is None:
                            v6_except = True
                            continue
                        ebase, eprefix = ebp
                        emask = _mask_for_prefix(eprefix)
                        exs.append((ebase & emask, emask))
                    if v6_except:
                        # mixed-family excepts: fall back to host eval for
                        # exactness
                        ip_rows[-1] = (0, 0, False)
                        exs = []
                        host_ip_rows.append((len(p_target) - 1, peer))
                    ex_rows.append(exs)
            elif isinstance(peer, PodPeerMatcher):
                p_kind.append(PEER_POD)
                specs.add(peer.port, vocab)
                ns = peer.namespace
                if isinstance(ns, ExactNamespaceMatcher):
                    p_ns_kind.append(NS_EXACT)
                    p_ns_id.append(vocab.ns_id(ns.namespace))
                    p_ns_sel.append(-1)
                elif isinstance(ns, LabelSelectorNamespaceMatcher):
                    p_ns_kind.append(NS_SELECTOR)
                    p_ns_id.append(-1)
                    p_ns_sel.append(sel_table.sel_id(ns.selector))
                elif isinstance(ns, AllNamespaceMatcher):
                    p_ns_kind.append(NS_ALL)
                    p_ns_id.append(-1)
                    p_ns_sel.append(-1)
                else:
                    raise TypeError(f"invalid NamespaceMatcher {type(ns)}")
                pod = peer.pod
                if isinstance(pod, AllPodMatcher):
                    p_pod_kind.append(POD_ALL)
                    p_pod_sel.append(-1)
                elif isinstance(pod, LabelSelectorPodMatcher):
                    p_pod_kind.append(POD_SELECTOR)
                    p_pod_sel.append(sel_table.sel_id(pod.selector))
                else:
                    raise TypeError(f"invalid PodMatcher {type(pod)}")
                ip_rows.append((0, 0, False))
                ex_rows.append([])
            else:
                raise TypeError(f"invalid PeerMatcher type {type(peer)}")

    n_p = len(p_target)
    max_x = max((len(x) for x in ex_rows), default=0)
    max_x = max(max_x, 1)
    ex_base = np.zeros((n_p, max_x), dtype=np.uint32)
    ex_mask = np.zeros((n_p, max_x), dtype=np.uint32)
    ex_valid = np.zeros((n_p, max_x), dtype=bool)
    for i, exs in enumerate(ex_rows):
        for j, (b, m) in enumerate(exs):
            ex_base[i, j] = b
            ex_mask[i, j] = m
            ex_valid[i, j] = True

    return _DirectionEncoding(
        n_targets=len(t_ns),
        target_ns=np.array(t_ns, dtype=np.int32).reshape(-1),
        target_sel=np.array(t_sel, dtype=np.int32).reshape(-1),
        peer_target=np.array(p_target, dtype=np.int32).reshape(-1),
        peer_rule_idx=np.array(p_rule_idx, dtype=np.int32).reshape(-1),
        peer_kind=np.array(p_kind, dtype=np.int32).reshape(-1),
        peer_ns_kind=np.array(p_ns_kind, dtype=np.int32).reshape(-1),
        peer_ns_id=np.array(p_ns_id, dtype=np.int32).reshape(-1),
        peer_ns_sel=np.array(p_ns_sel, dtype=np.int32).reshape(-1),
        peer_pod_kind=np.array(p_pod_kind, dtype=np.int32).reshape(-1),
        peer_pod_sel=np.array(p_pod_sel, dtype=np.int32).reshape(-1),
        ip_base=np.array([r[0] for r in ip_rows], dtype=np.uint32).reshape(-1),
        ip_mask=np.array([r[1] for r in ip_rows], dtype=np.uint32).reshape(-1),
        ip_is_v4=np.array([r[2] for r in ip_rows], dtype=bool).reshape(-1),
        ex_base=ex_base,
        ex_mask=ex_mask,
        ex_valid=ex_valid,
        host_ip_rows=host_ip_rows,
        port_spec=specs.encode(),
    )


@contracts.checked
@dataclass
class TierDirectionEncoding:
    """Precedence-tier rule slabs for one direction (docs/DESIGN.md
    "Precedence tiers").

    One row per (rule, peer scope) pair, flattened over BOTH admin tiers
    (`tier` 0=ANP, 1=BANP) in resolution order: `rank` is the rule's
    position in TierSet.ordered_rules for its tier, shared by all of the
    rule's peer rows — the first-match reduction is a min over matching
    rows of the int32 key rank * 4 + action, so equal-rank rows
    implement the within-rule peer OR exactly.  `action` is the int8
    verdict slab (TIER_ACT_*; 0 = pad, matches nothing — the inert fill
    shape bucketing uses).  Selector ids index the SAME deduped selector
    table as the NetworkPolicy slabs: subject/peer namespace selectors
    are evaluated against namespace labels (selns), pod selectors
    against pod labels (selpod), which is also what keeps the
    equivalence-class pod signature complete under tiers.

    Tensor contracts: G flat tier rows."""

    n_rules: int  # real (pre-flatten) rule count, both tiers
    subj_ns_sel: np.ndarray = contracts.tensor("(G,) int32")
    subj_pod_kind: np.ndarray = contracts.tensor("(G,) int32")  # POD_*
    subj_pod_sel: np.ndarray = contracts.tensor(
        "(G,) int32", sentinel="-1=pad"
    )
    peer_ns_sel: np.ndarray = contracts.tensor("(G,) int32")
    peer_pod_kind: np.ndarray = contracts.tensor("(G,) int32")
    peer_pod_sel: np.ndarray = contracts.tensor(
        "(G,) int32", sentinel="-1=pad"
    )
    action: np.ndarray = contracts.tensor("(G,) int8", sentinel="0=pad")
    tier: np.ndarray = contracts.tensor("(G,) int8")
    rank: np.ndarray = contracts.tensor("(G,) int32")
    port_spec: Dict[str, np.ndarray] = field(default_factory=dict)

    @property
    def n_rows(self) -> int:
        return int(self.action.shape[0])


def _encode_tier_direction(
    tiers, is_ingress: bool, sel_table: "_SelectorTable", vocab: _Vocab
) -> TierDirectionEncoding:
    """Flatten one direction of a TierSet into slab rows (see
    TierDirectionEncoding).  Selector ids are assigned through the
    SHARED table/vocab so tier selectors ride the same selpod/selns
    kernels as NetworkPolicy selectors."""
    from ..matcher.tiered import compile_tier_port_matcher

    subj_ns, subj_pk, subj_ps = [], [], []
    peer_ns, peer_pk, peer_ps = [], [], []
    action, tier_col, rank = [], [], []
    specs = _PortSpecBuilder()
    act_code = {
        "Allow": TIER_ACT_ALLOW,
        "Deny": TIER_ACT_DENY,
        "Pass": TIER_ACT_PASS,
    }
    n_rules = 0
    for tier_id, tier_name in ((TIER_ANP, "anp"), (TIER_BANP, "banp")):
        for o in tiers.ordered_rules(is_ingress, tier_name):
            n_rules += 1
            subject = o.policy.subject
            s_ns = sel_table.sel_id(subject.namespace_selector)
            if subject.pod_selector is None:
                s_pk, s_ps = POD_ALL, -1
            else:
                s_pk = POD_SELECTOR
                s_ps = sel_table.sel_id(subject.pod_selector)
            pm = compile_tier_port_matcher(o.rule)
            for peer in o.rule.peers:
                subj_ns.append(s_ns)
                subj_pk.append(s_pk)
                subj_ps.append(s_ps)
                peer_ns.append(sel_table.sel_id(peer.namespace_selector))
                if peer.pod_selector is None:
                    peer_pk.append(POD_ALL)
                    peer_ps.append(-1)
                else:
                    peer_pk.append(POD_SELECTOR)
                    peer_ps.append(sel_table.sel_id(peer.pod_selector))
                action.append(act_code[o.rule.action])
                tier_col.append(tier_id)
                rank.append(o.rank)
                specs.add(pm, vocab)
    return TierDirectionEncoding(
        n_rules=n_rules,
        subj_ns_sel=np.array(subj_ns, dtype=np.int32).reshape(-1),
        subj_pod_kind=np.array(subj_pk, dtype=np.int32).reshape(-1),
        subj_pod_sel=np.array(subj_ps, dtype=np.int32).reshape(-1),
        peer_ns_sel=np.array(peer_ns, dtype=np.int32).reshape(-1),
        peer_pod_kind=np.array(peer_pk, dtype=np.int32).reshape(-1),
        peer_pod_sel=np.array(peer_ps, dtype=np.int32).reshape(-1),
        action=np.array(action, dtype=np.int8).reshape(-1),
        tier=np.array(tier_col, dtype=np.int8).reshape(-1),
        rank=np.array(rank, dtype=np.int32).reshape(-1),
        port_spec=specs.encode(),
    )


def encode_tier_directions(
    tiers, sel_table: "_SelectorTable", vocab: _Vocab
) -> Tuple[TierDirectionEncoding, TierDirectionEncoding]:
    """(ingress, egress) tier slabs against the shared selector table."""
    return (
        _encode_tier_direction(tiers, True, sel_table, vocab),
        _encode_tier_direction(tiers, False, sel_table, vocab),
    )


@contracts.checked
@dataclass
class PolicyEncoding:
    """Full tensor encoding of a compiled Policy against a cluster.

    Selector-table contracts: S deduped selectors, R matchLabels pad
    width, E matchExpressions pad width, V expression-values pad
    width."""

    cluster: ClusterEncoding
    ingress: _DirectionEncoding
    egress: _DirectionEncoding
    # selector arrays (shared by both directions):
    sel_req_kv: np.ndarray = contracts.tensor("(S, R) int32", sentinel="-1=pad")
    sel_exp_op: np.ndarray = contracts.tensor("(S, E) int32")  # EXP_NONE pad
    sel_exp_key: np.ndarray = contracts.tensor("(S, E) int32", sentinel="-1=pad")
    sel_exp_vals: np.ndarray = contracts.tensor(
        "(S, E, V) int32", sentinel="-1=pad"
    )
    n_selectors: int
    # precedence-tier slabs (None on the networkingv1-only fast path —
    # the acceptance criterion: zero ANP/BANP objects leaves the tensor
    # set, and therefore every compiled program, byte-identical)
    tiers: Optional[Tuple[TierDirectionEncoding, TierDirectionEncoding]] = None


# --- equivalence-class grid compression ----------------------------------
#
# The verdict of pod n is a pure function of what the RESOLVED MATCHER SET
# can observe about n (kernel.py direction_precompute, term by term):
#   * tmatch:     target_ns == pod_ns_id[n]  AND  selpod[target_sel, n]
#   * pod peers:  ns kind (EXACT compares pod_ns_id; SELECTOR goes through
#                 selns[*, pod_ns_id[n]]) and selpod[peer_pod_sel, n]
#   * ip peers:   pod_ip_valid-masked CIDR membership per distinct
#                 (base, mask, excepts) row; host-evaluated v6 rows are a
#                 per-pod bool column of their own
# so the tuple (ns id, selector-match column, CIDR-membership bits,
# host-ip columns) is a COMPLETE signature: pods sharing it are
# indistinguishable to every rule and must receive identical verdict rows
# AND columns.  compute_pod_classes buckets pods by that signature; the
# evaluators then run the unique (src-class x dst-class x port) grid and
# broadcast back with an int32 gather (kernel.gather_class_grids) or an
# exact class-size weighting (tiled.evaluate_grid_counts_classes).
# Soundness is pinned three ways: the property suite hashes signatures
# against scalar-oracle verdict rows, the parity suite runs compressed vs
# dense vs oracle bit-identical, and analysis.audit_class_reduction
# oracle-checks co-classed pods at scale.


@contracts.checked
@dataclass
class PodClasses:
    """Label-equivalence classes over the pod axis.

    Tensor contracts: N pods, C classes.  class_of_pod maps pod row ->
    class id; class_rep is the first member (the row whose tensors stand
    in for the whole class); class_size the member count (the exact
    weight of a class cell when counts broadcast back to the pod grid).
    Validated on construction under CYCLONUS_SHAPE_CHECK=1."""

    n_pods: int
    n_classes: int
    class_of_pod: np.ndarray = contracts.tensor("(N,) int32")
    class_rep: np.ndarray = contracts.tensor("(C,) int32")
    class_size: np.ndarray = contracts.tensor("(C,) int32")
    # bytes per pod of the signature the classes were derived from
    signature_bytes: int = 0


def encode_pod_rows(
    pods: Sequence[Tuple[str, str, Dict[str, str], str]],
    vocab: _Vocab,
    l_width: int,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Encode pod tuples against an EXISTING vocab into fixed-width rows:
    (pod_ns_id [k], pod_kv [k, l_width], pod_key, pod_ip, pod_ip_valid).

    The delta path (cyclonus_tpu/serve) re-encodes ONLY the touched pod
    rows: the vocab grows monotonically (a label pair/key/namespace new
    to the cluster gets a fresh id, which by construction equals no
    selector-referenced id, so it matches nothing — exactly the fresh-
    rebuild semantics), and existing pairs resolve to their original
    ids, so a patched row is bit-compatible with the rows around it.
    Raises ValueError when a pod carries more labels than l_width — the
    caller's signal to fall back to a full re-encode."""
    k = len(pods)
    ns_id = np.empty((k,), dtype=np.int32)
    kv = np.full((k, max(l_width, 1)), -1, dtype=np.int32)
    key = np.full((k, max(l_width, 1)), -1, dtype=np.int32)
    for i, (ns, _name, labels, _ip) in enumerate(pods):
        if len(labels) > l_width:
            raise ValueError(
                f"pod row needs {len(labels)} label slots, row width is "
                f"{l_width} (full re-encode required)"
            )
        ns_id[i] = vocab.ns_id(ns)
        # sorted(items) mirrors _encode_label_rows' within-row order
        for j, (lk, lv) in enumerate(sorted(labels.items())):
            kv[i, j] = vocab.kv_id(lk, lv)
            key[i, j] = vocab.key_id(lk)
    ip, ip_valid = _encode_pod_ips([p[3] for p in pods])
    return ns_id, kv, key, ip, ip_valid


def encode_ns_row(
    labels: Dict[str, str], vocab: _Vocab, lns_width: int
) -> Tuple[np.ndarray, np.ndarray]:
    """One namespace-label row (ns_kv, ns_key) of width lns_width against
    an existing vocab; ValueError when the labels don't fit (full
    re-encode required)."""
    if len(labels) > lns_width:
        raise ValueError(
            f"namespace row needs {len(labels)} label slots, row width is "
            f"{lns_width} (full re-encode required)"
        )
    kv = np.full((max(lns_width, 1),), -1, dtype=np.int32)
    key = np.full((max(lns_width, 1),), -1, dtype=np.int32)
    for j, (lk, lv) in enumerate(sorted(labels.items())):
        kv[j] = vocab.kv_id(lk, lv)
        key[j] = vocab.key_id(lk)
    return kv, key


def encode_directions(
    policy: Policy, vocab: _Vocab, tiers=None
) -> Tuple[
    _DirectionEncoding,
    _DirectionEncoding,
    Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray],
    int,
    Optional[Tuple[TierDirectionEncoding, TierDirectionEncoding]],
]:
    """Encode both directions + the shared selector table of a compiled
    Policy against `vocab` (grown in place), plus — when `tiers` (a
    TierSet) is present and non-empty — the precedence-tier slabs, whose
    selector ids live in the SAME table (the table must close over both,
    or tier rows would index selectors the kernel never evaluates).

    This is the rule-slab half of encode_policy, split out so the delta
    path can re-encode a changed policy set against a LIVE engine's
    vocabulary: selector/target/peer ids are assigned fresh (they are
    slab-local), while label/namespace/port ids resolve through the
    shared vocab so the existing pod rows keep matching."""
    sel_table = _SelectorTable()
    ingress_targets, egress_targets = policy.sorted_targets()
    ingress = _encode_direction(ingress_targets, sel_table, vocab)
    egress = _encode_direction(egress_targets, sel_table, vocab)
    tier_enc = None
    if tiers:
        tier_enc = encode_tier_directions(tiers, sel_table, vocab)
    sel_arrays = sel_table.encode(vocab)
    return ingress, egress, sel_arrays, len(sel_table.selectors), tier_enc


def _host_ip_cols(tensors: Dict) -> List[np.ndarray]:
    """The host-evaluated (IPv6/mixed-family) ip rows' per-pod match
    columns, both directions — part of the signature on BOTH the dense
    bit path and the TSS path: the trie never sees a host row."""
    host_cols: List[np.ndarray] = []
    for direction in ("ingress", "egress"):
        d = tensors[direction]
        if "host_ip_mask" in d:
            for r in np.flatnonzero(d["host_ip_mask"]):
                host_cols.append(np.asarray(d["host_ip_match"][r], dtype=bool))
    return host_cols


def iter_ip_specs(
    tensors: Dict,
) -> List[Tuple[int, int, Tuple[Tuple[int, int], ...]]]:
    """Distinct (base, mask, sorted excepts) in-kernel IPv4 ip-peer
    specs across both directions, in discovery (row) order — THE spec
    identity that both the dense bit path (_ip_signature_bits) and the
    TSS stage (engine/cidrspace.py) bucket on.  One implementation on
    purpose: the spec count drives the TSS auto-mode floor and the bit
    path's signature width, so a drift between two copies would engage
    the stage at different counts than the dense path reports."""
    specs: Dict[Tuple[int, int, Tuple[Tuple[int, int], ...]], None] = {}
    for direction in ("ingress", "egress"):
        d = tensors[direction]
        rows = np.flatnonzero((d["peer_kind"] == PEER_IP) & d["ip_is_v4"])
        for r in rows:
            exs = tuple(
                sorted(
                    (int(d["ex_base"][r, j]), int(d["ex_mask"][r, j]))
                    for j in np.flatnonzero(d["ex_valid"][r])
                )
            )
            specs.setdefault(
                (int(d["ip_base"][r]), int(d["ip_mask"][r]), exs), None
            )
    return list(specs)


def _ip_signature_bits(tensors: Dict) -> Optional[np.ndarray]:
    """[N, ceil(B/8)] uint8 packed per-pod IP-observability bits, or None
    when no rule observes pod IPs.

    One bit per DISTINCT (base, mask, sorted excepts) IPv4 ip-peer row
    across both directions — the same membership term the kernel
    computes (in_cidr & ~in_except, both pod_ip_valid-masked) — plus one
    bit per host-evaluated (IPv6/mixed) row's match column, plus the
    validity bit itself.  Deduping rows first keeps the bit count at the
    number of distinct CIDR shapes, not the raw peer count.

    This is the DENSE path: O(specs) bits and O(specs x N) work per
    classify, which is exactly the wall a CIDR-heavy set hits — the TSS
    twin (_ip_signature_tss via engine/cidrspace.py) replaces the spec
    bits with [K] int32 partition signatures when the stage is active."""
    pod_ip = tensors["pod_ip"]  # shape: (N,) uint32; sentinel: 0=invalid; mask: pod_ip_valid
    pod_ip_valid = tensors["pod_ip_valid"]  # shape: (N,) bool
    n = int(pod_ip.shape[0])
    specs = iter_ip_specs(tensors)
    host_cols = _host_ip_cols(tensors)
    if not specs and not host_cols:
        return None
    bits = np.zeros((len(specs) + len(host_cols) + 1, n), dtype=bool)
    for i, (base, mask, exs) in enumerate(specs):
        # mirrors kernel.direction_precompute: both the CIDR term and
        # every except term consult pod_ip_valid (SC003 on pod_ip)
        m = pod_ip_valid & ((pod_ip & np.uint32(mask)) == np.uint32(base))
        for eb, em in exs:
            m &= ~(pod_ip_valid & ((pod_ip & np.uint32(em)) == np.uint32(eb)))
        bits[i] = m
    for j, col in enumerate(host_cols):
        bits[len(specs) + j] = col
    bits[-1] = pod_ip_valid
    return np.packbits(bits, axis=0).T  # [N, ceil(B/8)]


def _ip_signature_tss(tensors: Dict, cidr) -> np.ndarray:
    """[N, 4K + ceil((H+1)/8)] uint8 TSS signature block: the [K] int32
    per-pod partition signature (cidrspace.CidrSpace.signature — the
    device-resident LPM stage or its numpy twin) viewed as bytes, plus
    the packed host-evaluated columns and the validity bit.

    Sound for compute_pod_classes because pods with equal partition
    signatures match exactly the same atom in every partition, hence
    carry identical membership on every (base, mask, excepts) spec —
    the same bits _ip_signature_bits would emit, proven mechanically by
    cidrspace.spec_membership_words in the parity suite.  The TSS block
    may be FINER than the bit block (splitting costs classes, never
    correctness)."""
    pod_ip = tensors["pod_ip"]  # shape: (N,) uint32; sentinel: 0=invalid; mask: pod_ip_valid
    pod_ip_valid = tensors["pod_ip_valid"]  # shape: (N,) bool
    n = int(pod_ip.shape[0])
    sig = cidr.signature(pod_ip, pod_ip_valid)  # [K, N] int32
    # explicit width (not -1): numpy cannot infer a trailing dim for a
    # zero-size array, and n=0 must keep working (empty-cluster rebuild
    # on the serve path)
    blocks = [
        np.ascontiguousarray(sig.T)
        .view(np.uint8)
        .reshape(n, 4 * int(sig.shape[0]))
    ]
    host_cols = _host_ip_cols(tensors)
    tail = np.zeros((len(host_cols) + 1, n), dtype=bool)
    for j, col in enumerate(host_cols):
        tail[j] = col
    tail[-1] = pod_ip_valid
    blocks.append(np.packbits(tail, axis=0).T)
    return np.concatenate(blocks, axis=1)


#: `cidr` default for pod_signatures/compute_pod_classes: resolve the
#: TSS stage from the env + tensors (engine/cidrspace.py).  Distinct
#: from None, which means "explicitly dense bits" — the engine passes
#: its resolved space (or None) so build and serve can never disagree
CIDR_AUTO = "auto"


def pod_signatures(
    tensors: Dict, selpod: np.ndarray, cidr=CIDR_AUTO
) -> np.ndarray:
    """[N, K] uint8 packed per-pod observability signatures: ns id bytes
    + packed selector-match bits + the IP-observability block (see the
    class-compression design note above).  Pods with equal rows are
    indistinguishable to every rule.

    `cidr` selects the IP block's form: a cidrspace.CidrSpace routes the
    CIDR dimension through the TSS/LPM partition signature ([K] int32
    per pod — O(partitions), breaking the O(specs)-bits wall); None
    keeps the dense per-spec bits; CIDR_AUTO (default) resolves from the
    env/tensors, which derives the SAME space an engine build would.

    The delta path recomputes SINGLE rows of this matrix (one-pod
    `tensors` view + the pod's [S, 1] selpod column) to patch class
    membership without a full classify pass; the row width depends only
    on the selector count and the ip-peer spec/partition structure, so
    it is stable across pod-only deltas."""
    n = int(tensors["pod_ns_id"].shape[0])
    blocks = [
        np.ascontiguousarray(
            tensors["pod_ns_id"].astype(np.int32, copy=False).reshape(n, 1)
        ).view(np.uint8).reshape(n, 4)
    ]
    if selpod.shape[0]:
        if selpod.shape[1] != n:
            raise ValueError(
                f"selpod covers {selpod.shape[1]} pods but tensors hold {n}"
            )
        blocks.append(np.packbits(selpod, axis=0).T)  # [N, ceil(S/8)]
    if cidr is CIDR_AUTO:
        from .cidrspace import resolve as _resolve_cidr

        cidr = _resolve_cidr(tensors)
    if cidr is not None:
        blocks.append(_ip_signature_tss(tensors, cidr))
    else:
        ip_bits = _ip_signature_bits(tensors)
        if ip_bits is not None:
            blocks.append(ip_bits)
    return np.ascontiguousarray(np.concatenate(blocks, axis=1))


def classes_from_signatures(buf: np.ndarray) -> PodClasses:
    """PodClasses from a [N, K] signature matrix: one np.unique over the
    void row view (shared by the build-time classify and the delta
    path's class rebuild)."""
    n = int(buf.shape[0])
    if n == 0:
        z = np.zeros((0,), dtype=np.int32)
        return PodClasses(
            n_pods=0, n_classes=0, class_of_pod=z,
            class_rep=z.copy(), class_size=z.copy(),
        )
    rows = buf.view(np.dtype((np.void, buf.shape[1]))).reshape(n)
    _, rep, inv, counts = np.unique(
        rows, return_index=True, return_inverse=True, return_counts=True
    )
    return PodClasses(
        n_pods=n,
        n_classes=int(rep.size),
        class_of_pod=inv.astype(np.int32).reshape(n),
        class_rep=rep.astype(np.int32).reshape(-1),
        class_size=counts.astype(np.int32).reshape(-1),
        signature_bytes=int(buf.shape[1]),
    )


def compute_pod_classes(
    tensors: Dict, selpod: np.ndarray, cidr=CIDR_AUTO
) -> PodClasses:
    """Bucket pods into label-equivalence classes.

    `tensors` is the engine tensor dict BEFORE shape bucketing (real pod
    rows only); `selpod` the [S, N] host selector-match matrix over the
    same rows (api._selector_pod_matches_host — the identical pass that
    feeds dead-target compaction); `cidr` a resolved cidrspace.CidrSpace
    / None / CIDR_AUTO exactly as pod_signatures takes it.  Numpy plus
    the optional device LPM stage: one packed signature matrix, one
    np.unique over its void view."""
    n = int(tensors["pod_ns_id"].shape[0])
    if n == 0:
        return classes_from_signatures(np.zeros((0, 1), dtype=np.uint8))
    return classes_from_signatures(pod_signatures(tensors, selpod, cidr=cidr))


def gather_class_pod_rows(tensors: Dict, class_rep: np.ndarray) -> Dict:
    """The compressed tensor dict: per-pod arrays gathered at the class
    representatives (pod axis N -> class axis C); policy tensors shared
    by reference.  host_ip_match columns gather too — a host-evaluated
    row's column is part of the class signature, so the representative's
    value is the class value."""
    t = dict(tensors)
    for k in ("pod_ns_id", "pod_kv", "pod_key", "pod_ip", "pod_ip_valid"):
        t[k] = np.ascontiguousarray(t[k][class_rep])
    for direction in ("ingress", "egress"):
        d = t[direction]
        if "host_ip_match" in d:
            d = dict(d)
            d["host_ip_match"] = np.ascontiguousarray(
                d["host_ip_match"][:, class_rep]
            )
            t[direction] = d
    return t


def _rows_as_bytes(arrays: Sequence[np.ndarray]) -> np.ndarray:
    """[R, K] uint8 matrix whose row r concatenates the bytes of row r of
    every input array (1-D or 2-D; bools and ints alike)."""
    blocks = []
    r = int(arrays[0].shape[0])
    for a in arrays:
        a = np.ascontiguousarray(a)
        blocks.append(a.view(np.uint8).reshape(r, -1))
    return np.concatenate(blocks, axis=1)


def compress_rule_axes(d: Dict) -> Tuple[Dict, Dict[str, int]]:
    """Tuple-space partition compression of one direction's rule axes.

    Two exact reductions (verdicts depend on the target/peer axes only
    through OR-reductions, so duplicates are redundant):

      1. targets with identical (namespace, selector) merge into one row
         — their tmatch rows are equal, and ORing their peer sets under
         one row preserves any_allow > 0 and has_target exactly;
      2. flat peer rules that are byte-identical across every matcher
         array, their port-spec row, and their (merged) target collapse
         to one row — the peer->target one-hot matmul only feeds a > 0
         threshold, so multiplicity never matters.

    Host-evaluated ip rows (host_ip_mask) never merge: their [N] match
    columns live outside the row signature.  Returns the compressed
    direction dict + stats, including `partitions`: the number of
    distinct rule tuples ignoring the target — the tuple-space partition
    count in the TSS sense."""
    t_ns, t_sel = d["target_ns"], d["target_sel"]
    t = int(t_ns.shape[0])
    p = int(d["peer_target"].shape[0])
    stats = {
        "targets_before": t, "targets_after": t,
        "peers_before": p, "peers_after": p, "partitions": 0,
    }
    if t == 0 or p == 0:
        return d, stats
    tkey = np.stack([t_ns, t_sel], axis=1)
    uniq_t, t_inv = np.unique(tkey, axis=0, return_inverse=True)
    t_inv = t_inv.astype(np.int32).reshape(-1)
    pt = d["peer_target"]
    new_pt = np.where(pt >= 0, t_inv[np.clip(pt, 0, t - 1)], np.int32(-1))

    peer_arrays = [new_pt.reshape(-1, 1)]
    for k in (
        "peer_kind", "peer_ns_kind", "peer_ns_id", "peer_ns_sel",
        "peer_pod_kind", "peer_pod_sel", "ip_base", "ip_mask", "ip_is_v4",
        "ex_base", "ex_mask", "ex_valid",
    ):
        peer_arrays.append(d[k])
    for k in sorted(d["port_spec"]):
        peer_arrays.append(d["port_spec"][k])
    # host rows: a unique per-row tag keeps them out of every merge group
    host_tag = np.full((p,), -1, dtype=np.int32)
    if "host_ip_mask" in d:
        hr = np.flatnonzero(d["host_ip_mask"])
        host_tag[hr] = np.arange(hr.size, dtype=np.int32)
    peer_arrays.append(host_tag.reshape(-1, 1))
    key_bytes = _rows_as_bytes(peer_arrays)
    rows = np.ascontiguousarray(key_bytes).view(
        np.dtype((np.void, key_bytes.shape[1]))
    ).reshape(p)
    _, keep = np.unique(rows, return_index=True)
    keep = np.sort(keep)
    # partition count: same signature with the target column blanked
    part_bytes = key_bytes[:, 4:]
    part_rows = np.ascontiguousarray(part_bytes).view(
        np.dtype((np.void, part_bytes.shape[1]))
    ).reshape(p)
    stats["partitions"] = int(np.unique(part_rows).size)

    nd = dict(d)
    nd["target_ns"] = np.ascontiguousarray(uniq_t[:, 0].astype(np.int32))
    nd["target_sel"] = np.ascontiguousarray(uniq_t[:, 1].astype(np.int32))
    nd["peer_target"] = np.ascontiguousarray(new_pt[keep])
    for k in (
        "peer_kind", "peer_ns_kind", "peer_ns_id", "peer_ns_sel",
        "peer_pod_kind", "peer_pod_sel", "ip_base", "ip_mask", "ip_is_v4",
        "ex_base", "ex_mask", "ex_valid",
    ):
        nd[k] = np.ascontiguousarray(d[k][keep])
    if "host_ip_mask" in d:
        nd["host_ip_mask"] = np.ascontiguousarray(d["host_ip_mask"][keep])
    if "host_ip_match" in d:
        nd["host_ip_match"] = np.ascontiguousarray(d["host_ip_match"][keep])
    nd["port_spec"] = {
        k: np.ascontiguousarray(v[keep]) for k, v in d["port_spec"].items()
    }
    stats["targets_after"] = int(uniq_t.shape[0])
    stats["peers_after"] = int(keep.size)
    return nd, stats


def encode_policy(
    policy: Policy,
    pods: Sequence[Tuple[str, str, Dict[str, str], str]],
    namespaces: Dict[str, Dict[str, str]],
    tiers=None,
) -> PolicyEncoding:
    """Compile (policy, cluster) to tensors.  The selector/label vocabulary
    is built jointly so every selector-referenced pair has an id.  `tiers`
    (an optional TierSet) adds the precedence-tier slabs; with it absent or
    empty the encoding is byte-identical to the networkingv1-only form."""
    vocab = _Vocab()
    ingress, egress, sel_arrays, n_selectors, tier_enc = encode_directions(
        policy, vocab, tiers=tiers
    )
    cluster = encode_cluster(pods, namespaces, vocab=vocab)
    sel_req_kv, sel_exp_op, sel_exp_key, sel_exp_vals = sel_arrays
    return PolicyEncoding(
        cluster=cluster,
        ingress=ingress,
        egress=egress,
        sel_req_kv=sel_req_kv,
        sel_exp_op=sel_exp_op,
        sel_exp_key=sel_exp_key,
        sel_exp_vals=sel_exp_vals,
        n_selectors=n_selectors,
        tiers=tier_enc,
    )
