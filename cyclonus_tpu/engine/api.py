"""TpuPolicyEngine: the user-facing facade over the tensor compiler and
verdict kernels.

Replaces the reference's sequential simulated hot loop
(pkg/connectivity/probe/jobrunner.go:68-94): one engine evaluation computes
the whole pod x pod x port-case verdict grid on device.
"""

from __future__ import annotations

import ipaddress
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..kube.ipaddr import is_ip_address_match_for_ip_block
from ..matcher.core import Policy
from .encoding import PEER_IP, PolicyEncoding, _DirectionEncoding, encode_policy


@dataclass(frozen=True)
class PortCase:
    """One distinct (resolved port, resolved port name, protocol) tuple."""

    port: int
    port_name: str
    protocol: str


@dataclass
class GridVerdict:
    """Boolean verdict grids, numpy, indexed by the engine's pod order."""

    pod_keys: List[str]
    port_cases: List[PortCase]
    ingress: np.ndarray  # [Q, N_dst, N_src]
    egress: np.ndarray  # [Q, N_src, N_dst]
    combined: np.ndarray  # [Q, N_src, N_dst]

    def job_verdict(self, q_idx: int, src_idx: int, dst_idx: int):
        return (
            bool(self.ingress[q_idx, dst_idx, src_idx]),
            bool(self.egress[q_idx, src_idx, dst_idx]),
            bool(self.combined[q_idx, src_idx, dst_idx]),
        )


def _direction_tensors(enc: _DirectionEncoding) -> Dict:
    m_tp = np.zeros((enc.n_targets, enc.n_peers), dtype=bool)
    for p, t in enumerate(enc.peer_target):
        m_tp[t, p] = True
    d = {
        "target_ns": enc.target_ns,
        "target_sel": enc.target_sel,
        "peer_kind": enc.peer_kind,
        "peer_ns_kind": enc.peer_ns_kind,
        "peer_ns_id": enc.peer_ns_id,
        "peer_ns_sel": enc.peer_ns_sel,
        "peer_pod_kind": enc.peer_pod_kind,
        "peer_pod_sel": enc.peer_pod_sel,
        "ip_base": enc.ip_base,
        "ip_mask": enc.ip_mask,
        "ip_is_v4": enc.ip_is_v4,
        "ex_base": enc.ex_base,
        "ex_mask": enc.ex_mask,
        "ex_valid": enc.ex_valid,
        "m_tp": m_tp,
        "port_spec": dict(enc.port_spec),
    }
    return d


class TpuPolicyEngine:
    """Compile once per (policy set, cluster state); evaluate many port
    cases.  Pods are (namespace, name, labels, ip) tuples."""

    def __init__(
        self,
        policy: Policy,
        pods: Sequence[Tuple[str, str, Dict[str, str], str]],
        namespaces: Dict[str, Dict[str, str]],
    ):
        self.encoding: PolicyEncoding = encode_policy(policy, pods, namespaces)
        self._tensors = self._build_tensors()
        self._has_ip_peers = (
            bool(np.any(self.encoding.ingress.peer_kind == PEER_IP))
            or bool(np.any(self.encoding.egress.peer_kind == PEER_IP))
        )
        self._unparseable_ips = [
            ip
            for ip in self.encoding.cluster.pod_ips
            if not _parseable_ip(ip)
        ]

    @property
    def pod_keys(self) -> List[str]:
        return self.encoding.cluster.pod_keys

    def pod_index(self) -> Dict[str, int]:
        return {k: i for i, k in enumerate(self.pod_keys)}

    def _build_tensors(self) -> Dict:
        enc = self.encoding
        c = enc.cluster
        tensors = {
            "sel_req_kv": enc.sel_req_kv,
            "sel_exp_op": enc.sel_exp_op,
            "sel_exp_key": enc.sel_exp_key,
            "sel_exp_vals": enc.sel_exp_vals,
            "pod_ns_id": c.pod_ns_id,
            "pod_kv": c.pod_kv,
            "pod_key": c.pod_key,
            "pod_ip": c.pod_ip,
            "pod_ip_valid": c.pod_ip_valid,
            "ns_kv": c.ns_kv,
            "ns_key": c.ns_key,
            "ingress": _direction_tensors(enc.ingress),
            "egress": _direction_tensors(enc.egress),
        }
        for direction, denc in (("ingress", enc.ingress), ("egress", enc.egress)):
            if denc.host_ip_rows:
                # IPv6 / mixed-family IPBlocks: evaluate via the oracle's IP
                # matcher on host, inject as precomputed rows.
                n = c.n_pods
                mask = np.zeros((denc.n_peers,), dtype=bool)
                match = np.zeros((denc.n_peers, n), dtype=bool)
                for row, peer in denc.host_ip_rows:
                    mask[row] = True
                    for i, ip in enumerate(c.pod_ips):
                        match[row, i] = is_ip_address_match_for_ip_block(
                            ip, peer.ip_block
                        )
                tensors[direction]["host_ip_mask"] = mask
                tensors[direction]["host_ip_match"] = match
        return tensors

    def _port_case_arrays(self, cases: Sequence[PortCase]):
        vocab = self.encoding.cluster.vocab
        q_port = np.array([c.port for c in cases], dtype=np.int32)
        q_name = np.array(
            [vocab.port_name.get(c.port_name, -1) for c in cases], dtype=np.int32
        )
        # protocols unseen at compile time can match no spec: id -1 (pads
        # are -2, real ids >= 0)
        q_proto = np.array(
            [vocab.proto.get(c.protocol, -1) for c in cases], dtype=np.int32
        )
        return q_port, q_name, q_proto

    def _check_ips(self) -> None:
        if self._has_ip_peers and self._unparseable_ips:
            # The oracle raises when an IP peer matcher meets an unparseable
            # pod IP (kube/ipaddr.py); a grid evaluation hits every pair, so
            # raise with the same class of error.
            raise ValueError(
                f"unable to parse IP(s) {self._unparseable_ips[:3]!r} "
                f"while IPBlock peers are present"
            )

    def evaluate_grid(self, cases: Sequence[PortCase]) -> GridVerdict:
        """Single-device evaluation of the full N x N x Q verdict grid."""
        from .kernel import evaluate_grid_kernel

        self._check_ips()
        if not cases:
            n = self.encoding.cluster.n_pods
            empty = np.zeros((0, n, n), dtype=bool)
            return GridVerdict(self.pod_keys, [], empty, empty.copy(), empty.copy())
        q_port, q_name, q_proto = self._port_case_arrays(cases)
        tensors = dict(self._tensors)
        tensors["q_port"] = q_port
        tensors["q_name"] = q_name
        tensors["q_proto"] = q_proto
        out = evaluate_grid_kernel(tensors)
        # kernel layout: [target-side, peer-side, q] -> [q, ...]
        ingress = np.moveaxis(np.asarray(out["ingress"]), -1, 0)
        egress = np.moveaxis(np.asarray(out["egress"]), -1, 0)
        combined = np.moveaxis(np.asarray(out["combined"]), -1, 0)
        return GridVerdict(self.pod_keys, list(cases), ingress, egress, combined)

    def evaluate_grid_sharded(
        self, cases: Sequence[PortCase], mesh=None
    ) -> GridVerdict:
        """Mesh-sharded evaluation (source axis over devices); falls back to
        the single-device kernel when only one device is available."""
        from .sharded import evaluate_grid_sharded

        self._check_ips()
        if not cases:
            return self.evaluate_grid(cases)
        q_port, q_name, q_proto = self._port_case_arrays(cases)
        tensors = dict(self._tensors)
        tensors["q_port"] = q_port
        tensors["q_name"] = q_name
        tensors["q_proto"] = q_proto
        ingress, egress, combined = evaluate_grid_sharded(
            tensors, self.encoding.cluster.n_pods, mesh=mesh
        )
        return GridVerdict(
            self.pod_keys,
            list(cases),
            np.moveaxis(ingress, -1, 0),
            np.moveaxis(egress, -1, 0),
            np.moveaxis(combined, -1, 0),
        )


def _parseable_ip(ip: str) -> bool:
    try:
        ipaddress.ip_address(ip)
        return True
    except ValueError:
        return False
