"""TpuPolicyEngine: the user-facing facade over the tensor compiler and
verdict kernels.

Replaces the reference's sequential simulated hot loop
(pkg/connectivity/probe/jobrunner.go:68-94): one engine evaluation computes
the whole pod x pod x port-case verdict grid on device.
"""

from __future__ import annotations

import ipaddress
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..kube.ipaddr import is_ip_address_match_for_ip_block
from ..matcher.core import Policy
from ..telemetry import instruments as ti
from ..utils import guards
from ..utils.tracing import phase
from . import aot_cache, planspec
from .encoding import (
    PEER_IP,
    PolicyEncoding,
    _DirectionEncoding,
    compress_rule_axes,
    compute_pod_classes,
    encode_policy,
    gather_class_pod_rows,
    pack_enabled,
    packed_words,
)


@dataclass(frozen=True)
class PortCase:
    """One distinct (resolved port, resolved port name, protocol) tuple."""

    port: int
    port_name: str
    protocol: str


class GridVerdict:
    """Verdict grids.  The underlying arrays stay DEVICE-RESIDENT (host
    transfer of an N x N x Q grid dominates wall-clock at scale, especially
    over a tunneled TPU); numpy views materialize lazily on first access,
    and `gather` fetches individual cells with one device-side take."""

    def __init__(self, pod_keys, port_cases, ingress_dev, egress_dev, combined_dev):
        self.pod_keys: List[str] = pod_keys
        self.port_cases: List[PortCase] = port_cases
        # device arrays: ingress [Q, N_dst, N_src]; egress/combined
        # [Q, N_src, N_dst]
        self.ingress_dev = ingress_dev
        self.egress_dev = egress_dev
        self.combined_dev = combined_dev
        self._np: Dict[str, np.ndarray] = {}

    def block_until_ready(self) -> "GridVerdict":
        for a in (self.ingress_dev, self.egress_dev, self.combined_dev):
            if hasattr(a, "block_until_ready"):
                a.block_until_ready()
        return self

    def _materialize(self, name: str) -> np.ndarray:
        if name not in self._np:
            # NB: JAX dispatch is async, so this fetch phase also absorbs
            # any still-running device execution time (see engine.dispatch)
            with phase("grid.fetch"):
                self._np[name] = np.asarray(getattr(self, name + "_dev"))
        return self._np[name]

    @property
    def ingress(self) -> np.ndarray:
        return self._materialize("ingress")

    @property
    def egress(self) -> np.ndarray:
        return self._materialize("egress")

    @property
    def combined(self) -> np.ndarray:
        return self._materialize("combined")

    def job_verdict(self, q_idx: int, src_idx: int, dst_idx: int):
        return (
            bool(self.ingress[q_idx, dst_idx, src_idx]),
            bool(self.egress[q_idx, src_idx, dst_idx]),
            bool(self.combined[q_idx, src_idx, dst_idx]),
        )

    def gather(self, triples: Sequence[Tuple[int, int, int]]) -> np.ndarray:
        """Fetch (ingress, egress, combined) for a batch of (q, src, dst)
        triples with one device gather + one tiny transfer — no full-grid
        materialization."""
        import jax.numpy as jnp

        idx = np.array(triples, dtype=np.int32).reshape(-1, 3)
        if idx.shape[0] == 0:
            return np.zeros((0, 3), dtype=bool)
        q, s, d = idx[:, 0], idx[:, 1], idx[:, 2]
        out = jnp.stack(
            [
                self.ingress_dev[q, d, s],
                self.egress_dev[q, s, d],
                self.combined_dev[q, s, d],
            ],
            axis=1,
        )
        return np.asarray(out)

    def allow_stats(self) -> Dict[str, float]:
        """Device-side aggregate: mean allow rate per grid.  One fused
        execution and one 12-byte transfer — separate readbacks each pay a
        full round trip over a tunneled TPU."""
        if self.ingress_dev.shape[0] == 0:
            return {"ingress": 0.0, "egress": 0.0, "combined": 0.0}
        from .kernel import grid_stats_kernel

        stats = np.asarray(
            grid_stats_kernel(self.ingress_dev, self.egress_dev, self.combined_dev)
        )
        return {
            "ingress": float(stats[0]),
            "egress": float(stats[1]),
            "combined": float(stats[2]),
        }


def _direction_tensors(enc: _DirectionEncoding) -> Dict:
    # peer->target mapping ships as a [P] index vector; kernels build the
    # dense one-hot on device (kernel.m_tp_onehot) — the materialized
    # [T, P] matrix is ~70 MB at bench scale, dominating device_put time
    peer_target = np.asarray(enc.peer_target, dtype=np.int32).reshape(-1)
    d = {
        "target_ns": enc.target_ns,
        "target_sel": enc.target_sel,
        "peer_kind": enc.peer_kind,
        "peer_ns_kind": enc.peer_ns_kind,
        "peer_ns_id": enc.peer_ns_id,
        "peer_ns_sel": enc.peer_ns_sel,
        "peer_pod_kind": enc.peer_pod_kind,
        "peer_pod_sel": enc.peer_pod_sel,
        "ip_base": enc.ip_base,
        "ip_mask": enc.ip_mask,
        "ip_is_v4": enc.ip_is_v4,
        "ex_base": enc.ex_base,
        "ex_mask": enc.ex_mask,
        "ex_valid": enc.ex_valid,
        "peer_target": peer_target,
        "port_spec": dict(enc.port_spec),
    }
    return d


def _tier_tensors(tenc) -> Dict:
    """Tensor-dict view of one direction's TierDirectionEncoding
    (encoding.py): the int8 verdict + int32 rank slabs, the shared-table
    selector ids, and the per-row port spec."""
    return {
        "subj_ns_sel": tenc.subj_ns_sel,
        "subj_pod_kind": tenc.subj_pod_kind,
        "subj_pod_sel": tenc.subj_pod_sel,
        "peer_ns_sel": tenc.peer_ns_sel,
        "peer_pod_kind": tenc.peer_pod_kind,
        "peer_pod_sel": tenc.peer_pod_sel,
        "action": tenc.action,  # shape: (G,) int8; sentinel: 0=pad
        "tier": tenc.tier,
        "rank": tenc.rank,
        "port_spec": dict(tenc.port_spec),
    }


def _selector_match_np(
    sel_req_kv: np.ndarray,  # [S, R]
    sel_exp_op: np.ndarray,  # [S, E]
    sel_exp_key: np.ndarray,  # [S, E]
    sel_exp_vals: np.ndarray,  # [S, E, V]
    kv: np.ndarray,  # [N, L]
    key: np.ndarray,  # [N, L]
) -> np.ndarray:
    """[S, N] bool — numpy twin of kernel.selector_match, op for op.

    Pure numpy on purpose: the device twin would be routed to CPU with
    jax.devices("cpu"), and that call BLOCKS on global backend init —
    on a remote-attached TPU, encode would silently serialize behind
    seconds of tunnel bring-up.  Twin equality is pinned by
    tests/test_engine_pallas.py::test_selector_match_np_twin."""
    from .encoding import EXP_EXISTS, EXP_IN, EXP_NONE, EXP_NOT_IN

    present = np.any(
        kv[None, :, None, :] == sel_req_kv[:, None, :, None], axis=-1
    )
    req_ok = np.all((sel_req_kv[:, None, :] == -1) | present, axis=-1)  # [S, N]

    has_key = np.any(
        key[None, :, None, :] == sel_exp_key[:, None, :, None], axis=-1
    )  # [S, N, E]
    val_hit = np.any(
        (sel_exp_vals[:, None, :, :, None] != -1)
        & (kv[None, :, None, None, :] == sel_exp_vals[:, None, :, :, None]),
        axis=(-1, -2),
    )  # [S, N, E]
    op = sel_exp_op[:, None, :]  # [S, 1, E]
    exp_ok = np.where(
        op == EXP_NONE,
        True,
        np.where(
            op == EXP_IN,
            has_key & val_hit,
            np.where(
                op == EXP_NOT_IN,
                has_key & ~val_hit,
                np.where(op == EXP_EXISTS, has_key, ~has_key),
            ),
        ),
    )  # [S, N, E]
    return req_ok & np.all(exp_ok, axis=-1)


def _selector_pod_matches_host(tensors: Dict, chunk: int = 0) -> np.ndarray:
    """[S, N] bool selector-vs-pod matches, evaluated host-side in pod
    chunks so the result is available at encode time without touching any
    device.  The chunk scales inversely with the selector count so the
    [S, chunk, ...] broadcast intermediates stay bounded in BOTH axes —
    a fixed pod chunk would let a large selector table OOM the encode."""
    n = tensors["pod_kv"].shape[0]
    s = tensors["sel_req_kv"].shape[0]
    if not chunk:
        # budget the [S, chunk, R, L] and [S, chunk, E, V, L] broadcast
        # intermediates of _selector_match_np, not just S * chunk: a
        # label-heavy cluster (large R/E/V/L) scales the temporaries by
        # the trailing dims too
        r = tensors["sel_req_kv"].shape[1]
        e, v = tensors["sel_exp_vals"].shape[1:3]
        l = tensors["pod_kv"].shape[1]
        per_pod = max(s, 1) * max(r * l, e * v * l, 1)
        chunk = max(64, (1 << 24) // per_pod)
    outs = []
    for lo in range(0, n, chunk):
        outs.append(
            _selector_match_np(
                tensors["sel_req_kv"],
                tensors["sel_exp_op"],
                tensors["sel_exp_key"],
                tensors["sel_exp_vals"],
                tensors["pod_kv"][lo : lo + chunk],
                tensors["pod_key"][lo : lo + chunk],
            )
        )
    if not outs:
        return np.zeros((s, 0), dtype=bool)
    return np.concatenate(outs, axis=1)


# port_spec arrays are [P, ...]-shaped like the flat peer arrays
_PEER_KEYS = (
    "peer_kind",
    "peer_ns_kind",
    "peer_ns_id",
    "peer_ns_sel",
    "peer_pod_kind",
    "peer_pod_sel",
    "ip_base",
    "ip_mask",
    "ip_is_v4",
    "ex_base",
    "ex_mask",
    "ex_valid",
    "host_ip_mask",
)


def _compact_dead_targets(tensors: Dict, selpod: Optional[np.ndarray] = None) -> Dict:
    """Drop targets that match no pod of this cluster (and their peers).

    Verdicts are exactly invariant: a dead target's tmatch row is all
    False (kernel.direction_precompute), so it contributes nothing to
    has_target and nothing to any_allow.  But the target axis T is the
    flops multiplier of every grid kernel — and in namespace-local policy
    sets most compiled targets are dead ((ns, selector) combos with no
    matching pods), so compaction shrinks the dominant matmuls by the
    dead fraction.  Deadness is decided with the real selector kernel
    (no heuristics), evaluated once on CPU at encode time: O(S * N),
    noise next to the O(N^2 * T) evaluation it shrinks."""
    pod_ns_id = tensors["pod_ns_id"]
    if selpod is None:
        selpod = _selector_pod_matches_host(tensors)
    s = selpod.shape[0]
    # rows: any ns id referenced by pods or targets (vocab ns ids can
    # exceed the cluster's ns table when policies name pod-less namespaces)
    n_rows = int(tensors["ns_kv"].shape[0])
    for direction in ("ingress", "egress"):
        t_ns = tensors[direction]["target_ns"]
        if t_ns.size:
            n_rows = max(n_rows, int(t_ns.max()) + 1)
    if pod_ns_id.size:
        n_rows = max(n_rows, int(pod_ns_id.max()) + 1)
    # live_by_sel_ns[s, ns] = selector s matches >= 1 pod in namespace ns
    live_by_sel_ns = np.zeros((s, max(n_rows, 1)), dtype=bool)
    for si in range(s):
        ids = pod_ns_id[selpod[si]]
        if ids.size:
            live_by_sel_ns[si, ids[ids >= 0]] = True

    out = dict(tensors)
    for direction in ("ingress", "egress"):
        d = tensors[direction]
        t_ns, t_sel = d["target_ns"], d["target_sel"]
        t = t_ns.shape[0]
        if t == 0:
            continue
        live = (t_ns >= 0) & live_by_sel_ns[t_sel, np.maximum(t_ns, 0)]
        keep = np.flatnonzero(live)
        if keep.size == t:
            continue
        remap = np.full(t, -1, dtype=np.int32)
        remap[keep] = np.arange(keep.size, dtype=np.int32)
        pt = d["peer_target"]
        pkeep = (pt >= 0) & live[np.clip(pt, 0, t - 1)]
        nd = dict(d)
        nd["target_ns"] = np.ascontiguousarray(t_ns[keep])
        nd["target_sel"] = np.ascontiguousarray(t_sel[keep])
        nd["peer_target"] = np.ascontiguousarray(remap[pt[pkeep]])
        for k in _PEER_KEYS:
            if k in nd:
                nd[k] = np.ascontiguousarray(nd[k][pkeep])
        if "host_ip_match" in nd:
            nd["host_ip_match"] = np.ascontiguousarray(nd["host_ip_match"][pkeep])
        nd["port_spec"] = {
            k: np.ascontiguousarray(v[pkeep]) for k, v in d["port_spec"].items()
        }
        out[direction] = nd
    return out


def _sort_targets_by_ns(tensors: Dict) -> Dict:
    """Permute each direction's targets into namespace order (stable).

    Target order is semantically irrelevant — every kernel reduces over
    the target axis — but with targets ns-sorted (and pods ns-sorted at
    counts time) the tmatch matrices become near block diagonal, which
    is what lets the pallas counts kernel skip empty (pod-tile, T-chunk)
    blocks.  Sorting once in the base tensors means no per-path copy of
    the target/peer arrays is ever needed."""
    out = dict(tensors)
    for direction in ("ingress", "egress"):
        d = tensors[direction]
        t_ns = d["target_ns"]
        if t_ns.size == 0:
            continue
        tperm = np.argsort(t_ns, kind="stable")
        if np.array_equal(tperm, np.arange(tperm.size)):
            continue
        inv = np.empty_like(tperm)
        inv[tperm] = np.arange(tperm.size)
        nd = dict(d)
        nd["target_ns"] = np.ascontiguousarray(t_ns[tperm])
        nd["target_sel"] = np.ascontiguousarray(d["target_sel"][tperm])
        if d["peer_target"].size:
            nd["peer_target"] = np.ascontiguousarray(
                inv[d["peer_target"]].astype(np.int32)
            )
        out[direction] = nd
    return out


def _bucket_dim(n: int, lo: int = 4) -> int:
    """Shape bucket: next power of two up to 128, then multiples of 128
    (pod axis uses _bucket_pods).  Every distinct tensor shape costs a
    fresh XLA compile; the 216 conformance clusters differ by a few
    selectors/targets each, so exact sizing recompiled the engine per
    test case — bucketing collapses them onto a handful of programs.
    Above 128 the granule stays at 128 (the kernels' lane alignment):
    pow2 there would pad the target axis far past the pallas kernel's
    own chunk rounding and measurably deepen the contraction."""
    n = max(n, lo)
    if n <= 128:
        return 1 << (n - 1).bit_length()
    return -(-n // 128) * 128


def _bucket_up(n: int, steps: int) -> int:
    """`n` (already a _bucket_dim bucket) stepped UP `steps` buckets —
    the slab-headroom pre-reservation (serve engines reserve one extra
    bucket so bucket-crossing policy churn stays on the incremental
    patch path instead of forcing a full rebuild)."""
    for _ in range(max(0, steps)):
        n = _bucket_dim(n + 1)
    return n


def _bucket_down(n: int, steps: int) -> int:
    """Inverse of _bucket_up on the bucket ladder (4..128 pow2, then
    multiples of 128), floored at the smallest bucket.  Used to recover
    a slab's ZERO-HEADROOM bucket from its allocated (headroom-stepped)
    size when counting headroom saves."""
    for _ in range(max(0, steps)):
        if n > 256:
            n -= 128
        elif n == 256:
            n = 128
        else:
            n = max(4, n // 2)
    return n


def _bucket_pods(n: int) -> int:
    """Pod-axis bucket: pow2 up to 1024, then multiples of 1024 (matches
    the tile block, and keeps large-N padding waste under ~0.1%)."""
    n = max(n, 8)
    if n <= 1024:
        return 1 << (n - 1).bit_length()
    return -(-n // 1024) * 1024


def _pad_axis(a: np.ndarray, axis: int, size: int, fill) -> np.ndarray:
    """Pad `axis` up to `size` with `fill` (no-op when already there)."""
    cur = a.shape[axis]
    if cur >= size:
        return a
    widths = [(0, 0)] * a.ndim
    widths[axis] = (0, size - cur)
    return np.pad(a, widths, constant_values=fill)


# (array key, per-axis fill values) — the inert pad conventions from
# encoding.py's padding-neutrality invariants: padded selectors are
# unreferenced, padded targets match no pod (ns -1), padded peers belong
# to target -1 (zero one-hot row), padded port items/ranges match nothing
_SEL_PADS = {
    "sel_req_kv": -1,
    "sel_exp_op": 0,
    "sel_exp_key": -1,
    "sel_exp_vals": -1,
}
_DIRECTION_PADS = {
    "target_ns": -1,
    "target_sel": 0,
    "peer_target": -1,
    "peer_kind": 0,
    "peer_ns_kind": 0,
    "peer_ns_id": -1,
    "peer_ns_sel": 0,
    "peer_pod_kind": 0,
    "peer_pod_sel": 0,
    "ip_base": 0,
    "ip_mask": 0,
    "ip_is_v4": False,
    "ex_base": 0,
    "ex_mask": 0,
    "ex_valid": False,
    "host_ip_mask": False,
    "host_ip_match": False,
}
_PORT_SPEC_PADS = {
    "item_kind": -1,
    "item_port": 0,
    "item_name": -2,
    "item_proto": -2,
    "rng_from": 0,
    "rng_to": -1,
    "rng_proto": -2,
    "spec_all": False,
}
# tier-slab pads: action 0 = TIER_ACT_NONE — a padded rule row matches
# nothing (every kernel masks on action > 0), so selector/rank fills
# are inert by construction
_TIER_PADS = {
    "subj_ns_sel": 0,
    "subj_pod_kind": 0,
    "subj_pod_sel": -1,
    "peer_ns_sel": 0,
    "peer_pod_kind": 0,
    "peer_pod_sel": -1,
    "action": 0,
    "tier": 0,
    "rank": 0,
}


def _bucket_tensors(tensors: Dict, headroom: int = 0) -> Dict:
    """Pad every tensor dimension up to its shape bucket with the inert
    fill for that array, so near-identical problems share compiled
    programs.  Semantics are unchanged by construction: each pad value is
    the same inert encoding the encoder itself uses for ragged padding
    (verified by the parity suites, which run everything bucketed).

    `headroom` steps the RULE-SLAB row buckets (selector table, target/
    peer axes, tier rule rows) up that many extra buckets — the serve
    path's slab pre-reservation (CYCLONUS_SERVE_HEADROOM): the reserved
    rows are the same inert pads, so verdicts are unchanged, and a
    later policy patch that crosses the natural bucket boundary can pad
    into the reservation instead of changing compiled shapes."""
    from .sharded import _pad_pod_arrays

    t = dict(tensors)
    # selector tables: rows are unreferenced when padded (fills from
    # _SEL_PADS — the one table this and the serve patch path share)
    s = _bucket_up(_bucket_dim(t["sel_req_kv"].shape[0]), headroom)
    for k in ("sel_req_kv", "sel_exp_op", "sel_exp_key"):
        fill = _SEL_PADS[k]
        t[k] = _pad_axis(
            _pad_axis(t[k], 1, _bucket_dim(t[k].shape[1]), fill), 0, s, fill
        )
    ev = t["sel_exp_vals"]
    fill = _SEL_PADS["sel_exp_vals"]
    t["sel_exp_vals"] = _pad_axis(
        _pad_axis(
            _pad_axis(ev, 2, _bucket_dim(ev.shape[2]), fill),
            1, _bucket_dim(ev.shape[1]), fill,
        ),
        0, s, fill,
    )
    # namespace tables: padded rows are unreferenced (ns ids are real)
    m = _bucket_dim(t["ns_kv"].shape[0])
    for k in ("ns_kv", "ns_key"):
        t[k] = _pad_axis(
            _pad_axis(t[k], 1, _bucket_dim(t[k].shape[1]), -1), 0, m, -1
        )
    # pod label columns
    for k in ("pod_kv", "pod_key"):
        t[k] = _pad_axis(t[k], 1, _bucket_dim(t[k].shape[1]), -1)
    # per-direction policy tensors
    for direction in ("ingress", "egress"):
        d = dict(t[direction])
        # the pallas counts path appends ONE pseudo-target row
        # (pallas_kernel._augment): bucket to boundary - 1 so the
        # augmented axis lands exactly on the 128 chunk boundary instead
        # of spilling a whole extra chunk into the contraction
        nt = _bucket_up(_bucket_dim(d["target_ns"].shape[0] + 1), headroom) - 1
        np_ = _bucket_up(_bucket_dim(d["peer_kind"].shape[0]), headroom)
        for k, fill in _DIRECTION_PADS.items():
            if k not in d:
                continue
            size = nt if k.startswith("target_") else np_
            d[k] = _pad_axis(d[k], 0, size, fill)
            if k in ("ex_base", "ex_mask", "ex_valid"):
                d[k] = _pad_axis(d[k], 1, _bucket_dim(d[k].shape[1]), fill)
        spec = {}
        for k, fill in _PORT_SPEC_PADS.items():
            a = _pad_axis(d["port_spec"][k], 0, np_, fill)
            if a.ndim == 2:
                a = _pad_axis(a, 1, _bucket_dim(a.shape[1]), fill)
            spec[k] = a
        d["port_spec"] = spec
        t[direction] = d
    # precedence-tier slabs: the rule axis buckets like the peer axis,
    # padded with inert (action 0) rows
    if "tiers" in t:
        tiers = {}
        for direction in ("ingress", "egress"):
            d = dict(t["tiers"][direction])
            g = _bucket_up(_bucket_dim(d["action"].shape[0]), headroom)
            for k, fill in _TIER_PADS.items():
                d[k] = _pad_axis(d[k], 0, g, fill)
            spec = {}
            for k, fill in _PORT_SPEC_PADS.items():
                a = _pad_axis(d["port_spec"][k], 0, g, fill)
                if a.ndim == 2:
                    a = _pad_axis(a, 1, _bucket_dim(a.shape[1]), fill)
                spec[k] = a
            d["port_spec"] = spec
            tiers[direction] = d
        t["tiers"] = tiers
    # pod axis last: the inert-row scheme lives in _pad_pod_arrays
    n = t["pod_ns_id"].shape[0]
    t, _ = _pad_pod_arrays(t, n, _bucket_pods(n))
    return t


# device-resident precompute cache ceiling (the tallow tensors are
# [T, N, Q] bf16 — ~260 MB at the 100k x 10k bench, but multi-GB at
# multi-million-pod scale, where recomputing beats pinning HBM)
_PRE_CACHE_MAX_BYTES = 2 << 30


def _pre_cache_enabled() -> bool:
    """Repeat evaluations of one case set keep the precompute on device
    (CYCLONUS_PRE_CACHE=0 opts out)."""
    import os

    return os.environ.get("CYCLONUS_PRE_CACHE", "1") != "0"


def _compaction_enabled(tensors: Dict) -> bool:
    """Compaction is on by default (CYCLONUS_COMPACT=0 opts out), guarded
    by a host-work budget: the CPU selector pass is O(S * N) with small
    per-element constants — cap S * N so a pathological selector count
    can't stall encode."""
    import os

    setting = os.environ.get("CYCLONUS_COMPACT", "")
    if setting == "0":
        return False
    if setting == "1":
        return True  # explicit opt-in overrides the work budget
    s = int(tensors["sel_req_kv"].shape[0])
    n = int(tensors["pod_ns_id"].shape[0])
    r = int(tensors["sel_req_kv"].shape[1])
    e, v = (int(x) for x in tensors["sel_exp_vals"].shape[1:3])
    l = int(tensors["pod_kv"].shape[1])
    # budget ELEMENT OPS of the host selector pass (S * N * the trailing
    # broadcast dims of _selector_match_np), not just S * N: 2^32 ops is
    # ~seconds-to-a-minute of single-threaded numpy.  The old flat S * N
    # cap bounded memory but let a label-heavy cluster stall encode for
    # minutes — past this budget the compaction win is dwarfed by its
    # own cost, so skip it (CYCLONUS_COMPACT=1 forces it back on).
    ops = s * n * max(r * l, e * v * l, 1)
    if ops > 1 << 32:
        import logging

        logging.getLogger(__name__).info(
            "skipping dead-target compaction: host selector pass would "
            "cost ~%.1e element ops (budget 2^32); set CYCLONUS_COMPACT=1 "
            "to force it",
            float(ops),
        )
        return False
    return True


#: below this pod count the auto mode leaves the legacy paths untouched:
#: the compressed path's win is quadratic in cluster size, and tiny
#: clusters are where the per-engine second tensor set costs most
#: relative to the work saved (CYCLONUS_CLASS_MIN_PODS overrides)
_CLASS_AUTO_MIN_PODS = 2048
#: the weighted-count split keeps every device-side partial an exact f32
#: integer only while row sums stay below 2^24 (tiled.py class counts
#: design note) — larger clusters bypass compression entirely
_CLASS_MAX_PODS_EXACT = 1 << 24


def _class_compress_mode() -> str:
    """CYCLONUS_CLASS_COMPRESS: "auto" (default — engage above the pod
    floor when the class reduction is real), "1" (force, any size),
    "0" (off, incl. the rule-axis partition compression)."""
    import os

    return os.environ.get("CYCLONUS_CLASS_COMPRESS", "auto").lower()


def _class_auto_min_pods() -> int:
    import os

    try:
        return int(
            os.environ.get("CYCLONUS_CLASS_MIN_PODS", str(_CLASS_AUTO_MIN_PODS))
        )
    except ValueError:
        return _CLASS_AUTO_MIN_PODS


def _np_leaves(tree):
    """Flat iterator over the numpy leaves of a nested tensor dict."""
    if isinstance(tree, dict):
        for v in tree.values():
            yield from _np_leaves(v)
    elif isinstance(tree, np.ndarray):
        yield tree


def _pack_tensors(tree):
    """Pack a numpy pytree into one int32 buffer + an unpack function.

    A remote-attached (tunneled) TPU pays ~50-100 ms of round-trip
    overhead PER BUFFER, so device_put of the ~57-leaf tensor dict costs
    seconds even though it is only a few MB.  Packing every leaf into a
    single int32 buffer makes it one transfer; `unpack` rebuilds the
    pytree from the buffer with static slices + bitcasts and is designed
    to be traced INSIDE a consumer jit (so the unpack adds no extra
    dispatch or executable of its own).

    Returns (packed_int32_np, unpack) where unpack(buf_jnp) -> pytree.
    The per-leaf layout rides along as `unpack.metas_by_path`
    ({("ingress", "ip_base"): (dtype, shape, word_offset, n_words), ...})
    — the delta path (cyclonus_tpu/serve) uses it to scatter-patch
    touched rows of the device buffer without re-transferring anything
    else.  Every leaf starts on a fresh int32 word (tail bytes are
    zero-padded), so row patches never cross leaf boundaries."""
    from jax import tree_util as jtu

    path_leaves, treedef = jtu.tree_flatten_with_path(tree)
    leaves = [leaf for _path, leaf in path_leaves]
    paths = [
        tuple(getattr(k, "key", str(k)) for k in path)
        for path, _leaf in path_leaves
    ]
    metas = []  # (dtype, shape, word_offset, n_words)
    chunks = []
    off = 0
    for leaf in leaves:
        a = np.ascontiguousarray(leaf)
        if a.dtype not in (
            np.dtype(np.int32),
            np.dtype(np.uint32),
            np.dtype(bool),
            np.dtype(np.int8),
        ):
            # unpack below BITCASTS from int32 words; any other dtype
            # would be silently reinterpreted — fail loudly instead
            raise TypeError(f"_pack_tensors: unsupported leaf dtype {a.dtype}")
        raw = a.tobytes()
        pad = (-len(raw)) % 4
        if pad:
            raw += b"\0" * pad
        words = np.frombuffer(raw, dtype=np.int32)
        metas.append((a.dtype, a.shape, off, words.size))
        chunks.append(words)
        off += words.size
    packed = np.concatenate(chunks) if chunks else np.zeros(0, np.int32)

    def unpack(buf):
        import jax
        import jax.numpy as jnp
        from jax import tree_util as jtu2

        outs = []
        for dtype, shape, o, nw in metas:
            n = int(np.prod(shape))
            if n == 0:
                outs.append(jnp.zeros(shape, dtype=dtype))
                continue
            words = buf[o : o + nw]
            if dtype == np.bool_:
                flat = jax.lax.bitcast_convert_type(words, jnp.uint8)
                arr = flat.reshape(-1)[:n].astype(jnp.bool_)
            elif dtype == np.int8:
                # the tier action slab: 4 int8 lanes per packed word
                flat = jax.lax.bitcast_convert_type(words, jnp.int8)
                arr = flat.reshape(-1)[:n]
            elif dtype == np.uint32:
                arr = jax.lax.bitcast_convert_type(words, jnp.uint32)
            else:  # int32 (the only other dtype _pack_tensors accepts)
                arr = words
            outs.append(arr.reshape(shape))
        return jtu2.tree_unflatten(treedef, outs)

    unpack.metas_by_path = dict(zip(paths, metas))
    return packed, unpack




@guards.checked
class TpuPolicyEngine:
    """Compile once per (policy set, cluster state); evaluate many port
    cases.  Pods are (namespace, name, labels, ip) tuples.

    Threading model (docs/DESIGN.md "Lock discipline"): evaluations are
    issued from one thread at a time, but the autotune's abandoned
    candidate thread (run_bounded timeout) can outlive its call and race
    the issuing thread inside _slab_ops_for.  Everything that pair of
    threads shares for WRITING — the slab choice and the cached
    gathered operands — is guarded by _slab_lock; _pre_cache is written
    only by the issuing thread, and the one place the orphan reads it
    (_slab_ops_for's operand build) snapshots it once and treats a
    concurrent eviction as a contained candidate failure.  The rest of
    the per-engine caches stay single-threaded by contract.
    """

    # the guarded-by contract (tools/locklint.py LK001 statically; under
    # CYCLONUS_GUARD_CHECK=1 these become asserting descriptors)
    _slab_choice = guards.Guarded("_slab_lock")
    _slab_ops_cache = guards.Guarded("_slab_lock")
    _kernel_choice = guards.Guarded("_slab_lock")

    def __init__(
        self,
        policy: Policy,
        pods: Sequence[Tuple[str, str, Dict[str, str], str]],
        namespaces: Dict[str, Dict[str, str]],
        *,
        compact: Optional[bool] = None,
        class_compress: Optional[str] = None,
        cidr_tss: Optional[str] = None,
        tiers=None,
        slab_headroom: int = 0,
    ):
        # compact/class_compress override the CYCLONUS_COMPACT /
        # CYCLONUS_CLASS_COMPRESS env defaults per engine (None = env).
        # The serve layer builds its engines with compact=False — dead-
        # target compaction bakes "no pod matches this target" into the
        # tensors, and a pod delta can make a dead target live, so a
        # delta-oriented engine must keep every target resident.
        # tiers: an optional tiers.model.TierSet — AdminNetworkPolicy/
        # BANP precedence tiers layered over the NetworkPolicy verdict
        # (docs/DESIGN.md "Precedence tiers").  With it absent or empty,
        # the tensor set — and therefore every compiled program — is
        # byte-identical to the networkingv1-only engine.
        # every evaluation path below is jax-backed: first-touch setup of
        # the persistent compile cache happens here, not at import time
        from . import ensure_persistent_compile_cache

        ensure_persistent_compile_cache()
        self._opt_compact = compact
        self._opt_class_compress = class_compress
        # cidr_tss overrides CYCLONUS_CIDR_TSS for the TSS/LPM CIDR
        # pre-classification stage (engine/cidrspace.py; docs/DESIGN.md
        # "CIDR tuple-space pre-classification") — None = env
        self._opt_cidr_tss = cidr_tss
        # rule-slab headroom (extra _bucket_dim steps pre-reserved on
        # the selector/target/peer/tier row buckets).  0 for batch
        # engines; the serve path passes CYCLONUS_SERVE_HEADROOM so
        # bucket-crossing policy churn patches into the reservation
        # (serve/incremental.py patch_policy) instead of rebuilding.
        self._slab_headroom = max(0, int(slab_headroom or 0))
        self.tiers = tiers if tiers else None
        if self.tiers is not None:
            self.tiers.validate()
        with phase("engine.encode"):
            self.encoding: PolicyEncoding = encode_policy(
                policy, pods, namespaces, tiers=self.tiers
            )
            self._tensors = self._build_tensors()
            # one O(S*N) host selector pass serves both consumers: dead-
            # target compaction here and the slab-window plan later
            # (selector and pod axes are unchanged by compaction, only
            # padded by bucketing)
            self._selpod_prebucket = None
            compact_on = (
                _compaction_enabled(self._tensors)
                if compact is None
                else bool(compact)
            )
            if compact_on:
                with phase("engine.compact"):
                    self._selpod_prebucket = _selector_pod_matches_host(
                        self._tensors
                    )
                    self._tensors = _compact_dead_targets(
                        self._tensors, selpod=self._selpod_prebucket
                    )
            # equivalence-class grid compression (docs/DESIGN.md "Grid
            # compression"): tuple-space partition compression of the
            # rule axes is exact and cheap, so it applies whenever
            # compression isn't disabled outright; the pod-class state
            # additionally needs the host selector pass and a real
            # reduction (auto mode) before paying for a second tensor set
            self._partition_stats = None
            self._class_state = None
            mode = (
                _class_compress_mode()
                if class_compress is None
                else str(class_compress).lower()
            )
            if mode != "0":
                with phase("engine.partition"):
                    pstats = {}
                    for direction in ("ingress", "egress"):
                        nd, pstats[direction] = compress_rule_axes(
                            self._tensors[direction]
                        )
                        self._tensors[direction] = nd
                    self._partition_stats = pstats
                self._maybe_build_class_state(mode)
            self._tensors = _bucket_tensors(
                _sort_targets_by_ns(self._tensors),
                headroom=self._slab_headroom,
            )
            if self._class_state is not None:
                st = self._class_state
                st["ctensors"] = _bucket_tensors(
                    _sort_targets_by_ns(st.pop("ctensors_raw")),
                    headroom=self._slab_headroom,
                )
                # the gather/index tensors the compressed path pins on
                # device: class map + weights + the compressed tensor
                # buffer — counted against CYCLONUS_SLAB_MAX_BYTES by
                # the slab plan and the compressed-counts eligibility
                cb = int(st["ctensors"]["pod_ns_id"].shape[0])
                # the TSS partition tensors (trie map) charge the same
                # budget: the LPM stage must never over-commit the HBM
                # the compression exists to save
                cidr_bytes = (
                    st["cidr"].nbytes() if st.get("cidr") is not None else 0
                )
                st["aux_bytes"] = int(
                    self.encoding.cluster.n_pods * 4
                    + cb * 4
                    + sum(a.nbytes for a in _np_leaves(st["ctensors"]))
                    + cidr_bytes
                )
                ti.CLASS_AUX_BYTES.set(st["aux_bytes"])
        # wall-clock of the last tiered grid evaluation's dispatch
        # (detail.tiers.resolve_s; None until a tiered eval ran)
        self._tier_resolve_s = None
        # The trailing `# derived-from:` declarations below are the
        # cache-coherence contract tools/cachelint.py CC002 enforces:
        # a VALUE token means invalidate_after_patch must reset the
        # attribute after an in-place buffer patch; `shapes` marks a
        # compiled-program cache (shape-keyed, survives value patches);
        # `patched` marks state the serve patch path maintains itself.
        self._device_tensors = None  # derived-from: buffer (unpacked views)
        self._packed_buf = None  # derived-from: patched (scatter writes back)
        self._unpack = None  # derived-from: patched (layout fixed at build)
        # jit wrappers over the unpack closures, cached so the serve
        # layer's patch/invalidate cycle re-unpacks through the SAME
        # compiled program instead of retracing per patch
        self._unpack_jit = None  # derived-from: shapes
        self._class_unpack_jit = None  # derived-from: shapes
        # compressed-path device state (all lazy; None when no class
        # state): packed class-representative buffer + unpacked pytree,
        # the pod->class gather map, and the fused grid+gather program
        self._class_packed_buf = None  # derived-from: patched
        self._class_unpack = None  # derived-from: patched
        self._class_device_tensors = None  # derived-from: buffer
        self._class_of_dev = None  # derived-from: classes
        self._class_grid_jit = None  # derived-from: shapes
        self._pod_perm_dev = None  # derived-from: pod-rows (ns-order perm)
        self._pod_perm_host = None  # derived-from: pod-rows
        self._slab_plan_state = "unset"  # derived-from: buffer (window proof)
        # None = not yet tuned (auto mode times both at the first
        # steady-state call); True/False = slab kernel chosen/rejected
        self._slab_choice = None  # derived-from: buffer (re-timed)
        self._slab_autotune = None  # {"default_s", "slab_s"} once timed
        # the bit-packed dtype plan (docs/DESIGN.md "Bit-packed
        # kernel"): resolved ONCE per engine from CYCLONUS_PACK — the
        # compiled program set is a function of it, like the operand
        # dtype — and passed static everywhere
        self._pack = pack_enabled()
        # persistent AOT executable adapters (engine/aot_cache.py):
        # built lazily per program family; with CYCLONUS_AOT_CACHE off
        # they pass straight through to the plain jits
        self._grid_aot = None  # derived-from: shapes
        self._pairs_aot = None  # derived-from: shapes
        # the tuned counts configuration: None until the autotune (or a
        # persisted-cache adoption) picks one; then {"kernel":
        # "default"|"slab"|"packed", optional "bs"/"bd"}.  Shares
        # _slab_lock with _slab_choice so the pair can never be read
        # half-updated against the autotune's abandoned thread.
        self._kernel_choice = None  # derived-from: buffer (re-tuned)
        # autotune forensics for bench detail.pack: {"source":
        # search|cache|single, "search_s", "candidates": [...],
        # "noise_floor"} once the first steady-state call resolves it
        self._autotune_stats = None
        # slab HBM cost scales with the port-case count, but the plan and
        # choice persist for the engine's life; dispatch re-checks the
        # budget against the ACTUAL q (plan time budgets q=2)
        self._slab_bytes_per_case = None
        self._slab_budget = None
        # set after an autotune TIMEOUT: {"event": Event, "waited": bool}
        # — the abandoned candidate thread's completion marker; dispatches
        # gate on it (_drain_autotune_orphan)
        self._autotune_orphan = None
        # guards the (_slab_choice, _slab_ops_cache) pair: the autotune's
        # rejection writes and the ops-cache fill can race an abandoned
        # candidate thread still inside _slab_ops_for
        self._slab_lock = guards.lock()
        self._counts_packed_jit = None  # derived-from: shapes
        # steady-state counts: cache the device-resident precompute per
        # port-case set so repeat evaluations run only the pallas kernel
        self._pre_jit = None  # derived-from: shapes
        self._counts_from_pre_jit = None  # derived-from: shapes
        self._counts_from_pre_packed_jit = None  # derived-from: shapes
        self._pre_cache = None  # derived-from: buffer (cases key + pre pytree)
        # gathered slab operands, cached next to the pre: building them
        # per dispatch cost more than the slab's depth cut saved (r5)
        self._slab_ops_jit = None  # derived-from: shapes
        self._counts_from_slab_ops_jit = None  # derived-from: shapes
        self._slab_ops_cache = None  # derived-from: buffer (gathered ops)
        self._pre_cache_misses = 0  # derived-from: buffer
        self._pre_cache_declined = None  # derived-from: buffer (declined key)
        self._last_counts_key = None  # derived-from: buffer
        self._has_ip_peers = (
            bool(np.any(self.encoding.ingress.peer_kind == PEER_IP))
            or bool(np.any(self.encoding.egress.peer_kind == PEER_IP))
        )
        # pod_ip_valid=True already proves parseability (the encoder's
        # IPv4 fast path), so only the residue — IPv6 pods and garbage —
        # pays ipaddress.ip_address; at 100k all-IPv4 pods this pass was
        # ~0.5 s of redundant parsing
        self._unparseable_ips = [
            ip
            for ip, v4 in zip(
                self.encoding.cluster.pod_ips,
                self.encoding.cluster.pod_ip_valid,
            )
            if not v4 and not _parseable_ip(ip)
        ]

    @property
    def pod_keys(self) -> List[str]:
        return self.encoding.cluster.pod_keys

    def pod_index(self) -> Dict[str, int]:
        return {k: i for i, k in enumerate(self.pod_keys)}

    def invalidate_after_patch(self) -> None:
        """Reset every VALUE-derived device cache after the serve layer
        (cyclonus_tpu/serve) patches the packed buffer in place.  Shapes
        are unchanged by contract, so the compiled programs — unpack,
        grid/counts kernels, pairs — all stay valid and are reused; the
        precompute / slab-operand pins and the device tensor views are
        stale data and must rebuild from the patched buffer (device-side
        work only: no host re-encode, no re-device_put of the buffer).
        The slab plan's per-tile window proof is churn-stale too, so the
        slab path stays disabled until the next full rebuild."""
        self._device_tensors = None
        self._class_device_tensors = None
        self._class_of_dev = None
        self._pre_cache = None
        self._pre_cache_misses = 0
        self._pre_cache_declined = None
        self._last_counts_key = None
        ti.PRE_CACHE_BYTES.set(0)
        with self._slab_lock:
            self._slab_choice = None
            self._slab_ops_cache = None
            # a tuned PACKED tile stays valid (it is a function of the
            # unchanged shapes); any DENSE-plan choice dies with the
            # slab plan — keeping a tuned "default" while _slab_choice
            # resets would leave the pair incoherent and suppress the
            # re-tune the fresh plan deserves
            if self._kernel_choice is not None and (
                self._kernel_choice.get("kernel") != "packed"
            ):
                self._kernel_choice = None
        self._slab_plan_state = None
        self._selpod_prebucket = None
        # ns-sort permutation: pod ns ids may have changed; [N] int32 is
        # re-uploaded lazily (a touched index vector, not a slab)
        self._pod_perm_dev = None
        self._pod_perm_host = None

    def _aot_plan(self, extra: str = "") -> str:
        """The dtype-plan half of the persistent AOT executable key
        (engine/aot_cache.py): packed32 vs the dense operand dtype plus
        the tier flag.  Programs whose trace bakes per-engine constants
        (the unpack closures' leaf layout) append a metas digest via
        `extra` — two engines with equal buffer lengths but different
        leaf layouts must never share an executable."""
        from .pallas_kernel import _resolve_operand_dtype

        dtype = "packed32" if self._pack else _resolve_operand_dtype(None)
        plan = f"{dtype};tiered={self.tiers is not None}"
        return plan + (";" + extra if extra else "")

    @staticmethod
    def _metas_digest(unpack) -> str:
        """Stable digest of a _pack_tensors unpack closure's baked leaf
        layout ((dtype, shape, word offset) per path) — the part of an
        unpack-consuming program's identity the arg shapes alone can't
        see."""
        return aot_cache.digest(sorted(unpack.metas_by_path.items()))

    def aot_stats(self) -> Dict:
        """The per-process AOT executable-cache forensics (bench.py
        records them under detail.cold_start.aot_cache)."""
        return aot_cache.counters()

    def _build_tensors(self) -> Dict:
        enc = self.encoding
        c = enc.cluster
        tensors = {
            "sel_req_kv": enc.sel_req_kv,
            "sel_exp_op": enc.sel_exp_op,
            "sel_exp_key": enc.sel_exp_key,
            "sel_exp_vals": enc.sel_exp_vals,
            "pod_ns_id": c.pod_ns_id,
            "pod_kv": c.pod_kv,
            "pod_key": c.pod_key,
            "pod_ip": c.pod_ip,
            "pod_ip_valid": c.pod_ip_valid,
            "ns_kv": c.ns_kv,
            "ns_key": c.ns_key,
            "ingress": _direction_tensors(enc.ingress),
            "egress": _direction_tensors(enc.egress),
        }
        if enc.tiers is not None:
            tensors["tiers"] = {
                "ingress": _tier_tensors(enc.tiers[0]),
                "egress": _tier_tensors(enc.tiers[1]),
            }
        for direction, denc in (("ingress", enc.ingress), ("egress", enc.egress)):
            if denc.host_ip_rows:
                # IPv6 / mixed-family IPBlocks: evaluate via the oracle's IP
                # matcher on host, inject as precomputed rows.
                n = c.n_pods
                mask = np.zeros((denc.n_peers,), dtype=bool)
                match = np.zeros((denc.n_peers, n), dtype=bool)
                for row, peer in denc.host_ip_rows:
                    mask[row] = True
                    for i, ip in enumerate(c.pod_ips):
                        match[row, i] = is_ip_address_match_for_ip_block(
                            ip, peer.ip_block
                        )
                tensors[direction]["host_ip_mask"] = mask
                tensors[direction]["host_ip_match"] = match
        return tensors

    # --- equivalence-class grid compression ------------------------------

    def _maybe_build_class_state(self, mode: str) -> None:
        """Bucket pods into label-equivalence classes and keep the
        compressed tensor set when compression is forced (mode "1") or
        worth it (auto: above the pod floor with a real reduction).
        Reuses the SAME host selector pass dead-target compaction paid
        for; when compaction's work budget skipped that pass, auto mode
        skips classes too (forcing recomputes it)."""
        n = self.encoding.cluster.n_pods
        if n == 0 or n >= _CLASS_MAX_PODS_EXACT:
            return
        if mode != "1" and n < _class_auto_min_pods():
            return
        selpod = self._selpod_prebucket
        if selpod is None:
            if mode != "1":
                return
            selpod = self._selpod_prebucket = _selector_pod_matches_host(
                self._tensors
            )
        # TSS/LPM CIDR pre-classification (engine/cidrspace.py): when the
        # stage resolves (CYCLONUS_CIDR_TSS gate + distinct-spec floor +
        # HBM budget), the class signature's CIDR dimension rides the
        # [K] int32 partition signature instead of per-spec bits — the
        # O(specs)->O(partitions) cut that keeps classification feasible
        # on CIDR-heavy sets.  None = the dense bit path, byte-identical
        # to the pre-TSS signature.
        from . import cidrspace

        space = cidrspace.resolve(
            self._tensors, mode=self._opt_cidr_tss, n_pods=n
        )
        with phase("engine.classify"):
            pc = compute_pod_classes(self._tensors, selpod, cidr=space)
        if mode != "1" and pc.n_classes > int(0.9 * n):
            return  # no real reduction: the second tensor set isn't worth it
        self._class_state = {
            "classes": pc,
            "ratio": n / max(pc.n_classes, 1),
            "ctensors_raw": gather_class_pod_rows(self._tensors, pc.class_rep),
            "aux_bytes": 0,  # finalized after bucketing (engine __init__)
            "last_gather_s": None,
            "cidr": space,
        }
        ti.CLASS_PODS.set(n)
        ti.CLASS_COUNT.set(pc.n_classes)
        ti.CLASS_RATIO.set(self._class_state["ratio"])

    def pod_classes(self):
        """The PodClasses of the active compression state, or None when
        compression is off / bypassed for this engine (analysis's
        audit_class_reduction and bench.py consume this)."""
        st = self._class_state
        return st["classes"] if st is not None else None

    def _class_aux_bytes(self) -> int:
        """Device bytes of the compression's gather/index tensors —
        charged against CYCLONUS_SLAB_MAX_BYTES wherever that budget is
        gated, so the compressed path can never over-commit the HBM it
        exists to save."""
        st = self._class_state
        return int(st["aux_bytes"]) if st is not None else 0

    def class_compression_stats(self) -> Dict:
        """The grid-compression summary bench.py records as
        detail.class_compression: pods, classes, ratio, the last
        broadcast-back epilogue seconds, and the rule-axis partition
        stats."""
        n = self.encoding.cluster.n_pods
        st = self._class_state
        if st is None:
            return {
                "active": False,
                "pods": n,
                "classes": None,
                "ratio": None,
                "gather_s": None,
                "partitions": self._partition_stats,
            }
        pc = st["classes"]
        return {
            "active": True,
            "pods": n,
            "classes": pc.n_classes,
            "ratio": round(st["ratio"], 4),
            "gather_s": st["last_gather_s"],
            "signature_bytes": pc.signature_bytes,
            "aux_bytes": st["aux_bytes"],
            "partitions": self._partition_stats,
        }

    def cidr_stats(self) -> Dict:
        """The TSS/LPM CIDR pre-classification summary (bench.py records
        it under detail.cidr): whether the stage is active, the distinct
        spec/atom/partition counts, the last LPM stage wall-clock and
        whether it ran on device, and the partition-tensor bytes charged
        to the HBM budget."""
        st = self._class_state
        space = st.get("cidr") if st is not None else None
        if space is None:
            return {
                "active": False,
                "distinct_cidrs": None,
                "atoms": None,
                "partitions": None,
                "lpm_s": None,
                "device": None,
                "bytes": 0,
            }
        return {
            "active": True,
            "distinct_cidrs": space.n_specs,
            "atoms": space.n_atoms,
            "partitions": space.n_partitions,
            "max_bucket": space.max_bucket,
            "host_rows": space.n_host_rows,
            "lpm_s": space.last_lpm_s,
            "device": space.last_device,
            "bytes": space.nbytes(),
        }

    def tier_stats(self) -> Dict:
        """The precedence-tier summary bench.py records as detail.tiers
        on every line: whether the lattice is active, the ANP object /
        flat rule-row counts, and the wall-clock of the last tiered grid
        evaluation (resolve_s; None until one ran)."""
        if self.tiers is None:
            return {
                "active": False,
                "anp_count": 0,
                "rule_rows": 0,
                "banp": False,
                "resolve_s": None,
            }
        enc_t = self.encoding.tiers
        rows = sum(t.n_rows for t in enc_t) if enc_t is not None else 0
        return {
            "active": True,
            "anp_count": len(self.tiers.anps),
            "rule_rows": rows,
            "banp": self.tiers.banp is not None,
            "resolve_s": self._tier_resolve_s,
        }

    def _ctensors_with_cases(
        self, cases: Sequence[PortCase], device: bool = False
    ) -> Dict:
        """Compressed-tensor twin of _tensors_with_cases: the class-
        representative tensor set + port-case arrays, optionally through
        its own single-buffer device transfer."""
        q_port, q_name, q_proto = self._port_case_arrays(cases)
        st = self._class_state
        if device:
            import jax

            if self._class_device_tensors is None:
                buf = self._packed_transfer(
                    "_class_packed_buf", "_class_unpack", st["ctensors"]
                )
                if self._class_unpack_jit is None:
                    self._class_unpack_jit = aot_cache.AotProgram(
                        "unpack.classes",
                        jax.jit(self._class_unpack),
                        plan=self._aot_plan(
                            self._metas_digest(self._class_unpack)
                        ),
                    )
                self._class_device_tensors = self._class_unpack_jit(buf)
            tensors = dict(self._class_device_tensors)
        else:
            tensors = dict(st["ctensors"])
        tensors["q_port"] = q_port
        tensors["q_name"] = q_name
        tensors["q_proto"] = q_proto
        return tensors

    def _class_counts_eligible(self, q: int) -> bool:
        """The compressed counts route must itself fit the HBM budget it
        protects: aux/index tensors + the class precompute + row sums,
        all estimated host-side before any dispatch."""
        st = self._class_state
        if st is None:
            return False
        from ..utils import envflags

        budget = envflags.get_int("CYCLONUS_SLAB_MAX_BYTES")
        ct = st["ctensors"]
        cb = int(ct["pod_ns_id"].shape[0])
        t = sum(
            int(ct[d]["target_ns"].shape[0]) for d in ("ingress", "egress")
        )
        if self._pack:
            # packed plan: tallow_pk int32 [W, Cb, Q] + tmatch_pk
            # [W, Cb] + the bool tmatch — ~16x below the bf16 estimate
            # (the _pre_bytes_estimate twin; overstating it here would
            # silently decline the compressed route at exactly the
            # watch-scale sizes it exists for)
            w = sum(
                packed_words(int(ct[d]["target_ns"].shape[0]))
                for d in ("ingress", "egress")
            )
            est = st["aux_bytes"] + cb * (4 * w * (q + 1) + t) + cb * q * 12
        else:
            # tallow bf16 [T, Cb, Q] per direction + tmatch + f32 row sums
            est = st["aux_bytes"] + t * cb * (2 * q + 1) + cb * q * 12
        return est <= budget

    def _counts_classes(
        self,
        cases: Sequence[PortCase],
        n: int,
        *,
        sharded: bool = False,
        block: int = 1024,
        mesh=None,
    ) -> Dict[str, int]:
        """Compressed counts: class-grid weighted row sums on device
        (single-device, or class-axis-sharded over `mesh`), exact int64
        class-size weighting on host (tiled.py).  One epilogue for both
        routes so the stats/telemetry can never diverge."""
        st = self._class_state
        pc = st["classes"]
        if sharded:
            planspec.record("counts.sharded.classes")
            from .tiled import evaluate_grid_counts_classes_sharded

            counts, gather_s = evaluate_grid_counts_classes_sharded(
                self._ctensors_with_cases(cases),
                pc.n_classes,
                pc.class_size,
                n,
                block=block,
                mesh=mesh,
            )
        else:
            planspec.record("counts.classes")
            from .tiled import evaluate_grid_counts_classes

            counts, gather_s = evaluate_grid_counts_classes(
                self._ctensors_with_cases(cases, device=True),
                pc.n_classes,
                pc.class_size,
                n,
                pack=self._pack,
            )
        st["last_gather_s"] = gather_s
        ti.CLASS_GATHER_SECONDS.set(gather_s)
        ti.CLASS_EVALS.inc(path="sharded" if sharded else "counts")
        return counts

    def _evaluate_grid_classes(self, cases: Sequence[PortCase]) -> GridVerdict:
        """Compressed grid path: evaluate the C x C x Q class grid and
        broadcast back to pod axes with the int32 gather epilogue —
        kernel + gather trace into ONE jit, so the path keeps the dense
        path's single-execution property."""
        import jax

        from .kernel import evaluate_grid_kernel, gather_class_grids

        planspec.record("grid.classes")
        st = self._class_state
        n = self.encoding.cluster.n_pods
        with ti.eval_flight(
            "grid.classes",
            n,
            len(cases),
            classes=st["classes"].n_classes,
            dispatch_only=True,
        ):
            tensors = self._ctensors_with_cases(cases, device=True)
            if self._class_of_dev is None:
                with phase("engine.device_put"):
                    self._class_of_dev = jax.device_put(
                        st["classes"].class_of_pod
                    )
            if self._class_grid_jit is None:
                pack = self._pack
                self._class_grid_jit = aot_cache.AotProgram(
                    "grid.classes",
                    jax.jit(
                        lambda t, co: gather_class_grids(
                            evaluate_grid_kernel(t, pack=pack), co
                        )
                    ),
                    plan=self._aot_plan(),
                )
            t0 = time.perf_counter()
            with phase("engine.dispatch"):
                out = self._class_grid_jit(tensors, self._class_of_dev)
            if self.tiers is not None:
                self._tier_resolve_s = time.perf_counter() - t0
            ti.CLASS_EVALS.inc(path="grid")
        return GridVerdict(
            self.pod_keys,
            list(cases),
            out["ingress"],
            out["egress"],
            out["combined"],
        )

    def _evaluate_grid_sharded_classes(
        self, cases: Sequence[PortCase], mesh, schedule=None
    ) -> GridVerdict:
        """Compressed mesh path: the shard_map program runs over the
        class axis — with the ring schedule, a C x C ring over class
        representatives; the gather epilogue broadcasts back to pod
        axes device-side (sharded.evaluate_class_grid_sharded)."""
        import jax.numpy as jnp

        from .sharded import evaluate_class_grid_sharded

        planspec.record("grid.sharded.classes")
        st = self._class_state
        pc = st["classes"]
        tensors = self._ctensors_with_cases(cases)
        with phase("engine.dispatch_sharded"):
            ingress, egress, combined = evaluate_class_grid_sharded(
                tensors, pc.n_classes, pc.class_of_pod, mesh=mesh,
                schedule=schedule,
            )
        ti.CLASS_EVALS.inc(path="sharded")
        return GridVerdict(
            self.pod_keys,
            list(cases),
            jnp.moveaxis(ingress, -1, 0),
            jnp.moveaxis(egress, -1, 0),
            jnp.moveaxis(combined, -1, 0),
        )

    def _pipelined_classes(self, cases: Sequence[PortCase], reps: int):
        """Compressed twin of the pipelined steady-state measurement:
        `reps` async dispatches of the class row-sum program, one
        readback, the same exact host finish."""
        import time as _time

        from .tiled import (
            _class_rowsums_kernel,
            class_counts_finish,
            class_rowsums_plan,
        )

        st = self._class_state
        pc = st["classes"]
        n = self.encoding.cluster.n_pods
        tensors = self._ctensors_with_cases(cases, device=True)
        w, block, n_tiles = class_rowsums_plan(
            tensors, pc.n_classes, pc.class_size
        )
        out = _class_rowsums_kernel(tensors, w, block, n_tiles, self._pack)
        np.asarray(out)  # warm barrier
        t0 = _time.perf_counter()
        outs = [
            _class_rowsums_kernel(tensors, w, block, n_tiles, self._pack)
            for _ in range(reps)
        ]
        rs = np.asarray(outs[-1])  # in-order stream: one barrier
        dt = (_time.perf_counter() - t0) / reps
        counts = class_counts_finish(
            rs, pc.class_size, pc.n_classes, len(cases), n
        )
        if dt > 0:
            ti.EVAL_DEVICE_SECONDS.set(dt)
            ti.EVAL_PIPELINED_CELLS_PER_SEC.set(counts["cells"] / dt)
        return dt, counts

    def _port_case_arrays(self, cases: Sequence[PortCase]):
        vocab = self.encoding.cluster.vocab
        q_port = np.array([c.port for c in cases], dtype=np.int32)  # shape: (Q,) int32
        q_name = np.array(
            [vocab.port_name.get(c.port_name, -1) for c in cases], dtype=np.int32
        )  # shape: (Q,) int32; sentinel: -1=unnamed
        # protocols unseen at compile time can match no spec: id -1 (pads
        # are -2, real ids >= 0)
        q_proto = np.array(
            [vocab.proto.get(c.protocol, -1) for c in cases], dtype=np.int32
        )
        return q_port, q_name, q_proto

    def _check_ips(self) -> None:
        if self._has_ip_peers and self._unparseable_ips:
            # The oracle raises when an IP peer matcher meets an unparseable
            # pod IP (kube/ipaddr.py); a grid evaluation hits every pair, so
            # raise with the same class of error.
            raise ValueError(
                f"unable to parse IP(s) {self._unparseable_ips[:3]!r} "
                f"while IPBlock peers are present"
            )

    def evaluate_grid(self, cases: Sequence[PortCase]) -> GridVerdict:
        """Single-device evaluation of the full N x N x Q verdict grid.
        Results stay on device (see GridVerdict)."""
        from .kernel import evaluate_grid_kernel

        self._check_ips()
        if not cases:
            n = self.encoding.cluster.n_pods
            empty = np.zeros((0, n, n), dtype=bool)
            return GridVerdict(self.pod_keys, [], empty, empty.copy(), empty.copy())
        if self._class_state is not None:
            return self._evaluate_grid_classes(cases)
        planspec.record("grid.dense")
        n = self.encoding.cluster.n_pods
        if self._grid_aot is None:
            self._grid_aot = aot_cache.AotProgram(
                "grid",
                evaluate_grid_kernel,
                plan=self._aot_plan(),
                static_argnames=("pack",),
            )
        with ti.eval_flight("grid", n, len(cases), dispatch_only=True):
            tensors = self._tensors_with_cases(cases, device=True)
            # dispatch-only timing: jit calls return once enqueued (async);
            # device execution time lands in grid.fetch / allow_stats
            t0 = time.perf_counter()
            with phase("engine.dispatch"):
                out = self._grid_aot(tensors, pack=self._pack)
            if self.tiers is not None:
                self._tier_resolve_s = time.perf_counter() - t0
        # kernel emits [q, ...] layout directly: one device execution
        # total.  Bucketing pads the pod axis; the lazy device slice
        # strips the pad rows so GridVerdict stays exactly n x n.
        return GridVerdict(
            self.pod_keys,
            list(cases),
            out["ingress"][:, :n, :n],
            out["egress"][:, :n, :n],
            out["combined"][:, :n, :n],
        )

    def _packed_transfer(self, buf_attr: str, unpack_attr: str, tensors: Dict):
        """Single-buffer device copy with per-engine caching (one
        transfer — per-buffer tunnel round trips dominate a multi-leaf
        device_put)."""
        if getattr(self, buf_attr) is None:
            import jax

            with phase("engine.device_put"):
                packed, unpack = _pack_tensors(tensors)
                setattr(self, buf_attr, jax.device_put(packed))
                setattr(self, unpack_attr, unpack)
        return getattr(self, buf_attr)

    def _ensure_packed(self):
        """Packed device buffer of the caller-order tensors (grid paths)."""
        return self._packed_transfer("_packed_buf", "_unpack", self._tensors)

    def _tensors_with_cases(
        self, cases: Sequence[PortCase], device: bool = False
    ) -> Dict:
        """Tensors + port-case arrays.  device=True reuses the packed
        device buffer (paths that don't re-pad the pod axis host-side)."""
        q_port, q_name, q_proto = self._port_case_arrays(cases)
        if device:
            import jax

            if self._device_tensors is None:
                buf = self._ensure_packed()
                if self._unpack_jit is None:
                    self._unpack_jit = aot_cache.AotProgram(
                        "unpack",
                        jax.jit(self._unpack),
                        plan=self._aot_plan(self._metas_digest(self._unpack)),
                    )
                self._device_tensors = self._unpack_jit(buf)
            tensors = dict(self._device_tensors)
        else:
            tensors = dict(self._tensors)
        tensors["q_port"] = q_port
        tensors["q_name"] = q_name
        tensors["q_proto"] = q_proto
        return tensors

    def evaluate_grid_counts(
        self,
        cases: Sequence[PortCase],
        block: int = 1024,
        backend: Optional[str] = None,
    ) -> Dict[str, int]:
        """Tiled full-grid allow counts for grids too large to materialize
        (one device execution, one small readback).  The default picks
        per platform: "pallas" — the fused verdict+count kernel
        (engine/pallas_kernel.py; adaptive tile sizes, `block` ignored),
        the fastest path at every measured scale — on TPU, where it
        compiles via Mosaic; "xla" — the lax.fori_loop tile loop
        (engine/tiled.py) — elsewhere, where pallas would fall back to
        slow interpret mode.  Identical results by construction; pass
        backend explicitly to force either."""
        explicit = backend is not None
        if backend is None:
            import jax

            backend = "pallas" if jax.default_backend() == "tpu" else "xla"
        if backend not in ("xla", "pallas"):
            raise ValueError(
                f"unknown counts backend {backend!r} (want 'xla' or "
                f"'pallas'; mesh-parallel = evaluate_grid_counts_sharded)"
            )
        # tiers x pallas: the decision (legal under the packed fused
        # tier epilogue; else fallback on auto, loud failure on an
        # explicit request — silently rewriting it would let a benchmark
        # publish the XLA rate under the pallas label) is a declared
        # cell of the planspec compatibility matrix, resolved there so
        # the declaration and the dispatch cannot drift
        backend = planspec.resolve_counts_backend(
            backend=backend,
            explicit=explicit,
            tiers=self.tiers is not None,
            pack=self._pack,
            packed_tier_ok=self._packed_tier_ok,
        )
        self._check_ips()
        n = self.encoding.cluster.n_pods
        if not cases or n == 0:
            return {"ingress": 0, "egress": 0, "combined": 0, "cells": 0}
        if self._class_state is not None and self._class_counts_eligible(
            len(cases)
        ):
            # compressed route (either backend: identical by construction;
            # the class grid is small enough that the XLA tile loop is
            # already device-bound) — bypassed when the estimate would
            # blow the HBM budget, falling back to the dense kernels
            return self._counts_classes(cases, n)
        if backend == "pallas":
            return self._counts_pallas_packed(cases, n)
        planspec.record("counts.xla")
        from .tiled import evaluate_grid_counts

        # the xla path pads the pod axis with numpy before dispatch
        return evaluate_grid_counts(
            self._tensors_with_cases(cases), n, block=block, pack=self._pack
        )

    def _packed_tier_ok(self) -> bool:
        """The fused tier epilogue unrolls statically over the bucketed
        rule rows (pallas_kernel.PACKED_TIER_MAX_ROWS); past the
        ceiling tiered counts fall back to the XLA tile loop.  Shared
        implementation with the fused class-counts route
        (pallas_kernel.packed_tier_eligible) so the two gates cannot
        drift."""
        from .pallas_kernel import packed_tier_eligible

        return packed_tier_eligible(self._tensors)

    def _pre_bytes_estimate(self, q: int) -> int:
        """Host-side size estimate of the precompute pytree (dominated by
        the per-direction [T, N, Q] tallow tensors): deciding the cache
        cap BEFORE dispatching the split path matters at multi-million-pod
        scale, where compiling the split programs just to find the result
        uncacheable cost ~8 minutes on the remote compile service."""
        n = int(self._tensors["pod_ns_id"].shape[0])
        t = sum(
            int(self._tensors[d]["target_ns"].shape[0])
            for d in ("ingress", "egress")
        )
        if self._pack:
            # packed plan: tallow_pk int32 [W, N, Q] + tmatch_pk [W, N]
            # + the bool tmatch [T, N] — ~16x below the bf16 estimate
            w = sum(
                packed_words(int(self._tensors[d]["target_ns"].shape[0]))
                for d in ("ingress", "egress")
            )
            return n * (4 * w * (q + 1) + t)
        # tallow bf16 [T, N, Q] per direction + tmatch bool [T, N] + small
        return t * n * (2 * q + 1)

    def _slab_plan(self, perm: np.ndarray):
        """Per-tile target-slab windows for the pallas slab kernel, or
        None when it doesn't apply.

        Host-side eligibility with the SAME reduction the kernel's
        safety rests on: per direction, every pod tile's matching
        targets (on the ns-sorted axis = perm order) must fit one
        SLAB_W window (pallas_kernel.slab_windows).  CYCLONUS_PALLAS_SLAB
        modes: "auto" (default) plans on TPU and lets the first
        steady-state call TIME both programs and keep the winner
        (_autotune_slab) — the depth-cut win only exists on hardware and
        interpret-mode timing is meaningless, so auto never engages off
        TPU; "1" forces the slab kernel (how CPU tests and the bench
        parity case exercise it); "0" disables.  Also requires the
        cluster to span at least two src tiles (below that the
        single-chunk kernel is already minimal) and the materialized
        slabs to fit the byte budget.  The numpy tmatch twin here is the
        same formula as kernel.direction_precompute, O(T*N) once per
        engine."""
        import os

        from .pallas_kernel import (
            SLAB_BD,
            SLAB_BS,
            SLAB_W,
            _resolve_operand_dtype,
            slab_w_aug,
            slab_windows,
        )

        if self._pack:
            # the packed kernel contracts over ceil(T/32) words — a far
            # deeper depth cut than the slab window, from the SAME
            # precompute with no gathered-operand HBM pin — so the slab
            # path (and its multi-second host window pass) is retired
            # under the packed dtype plan; CYCLONUS_PACK=0 restores it
            return None
        mode = os.environ.get("CYCLONUS_PALLAS_SLAB", "auto").lower()
        if mode == "auto":
            import jax

            if jax.default_backend() != "tpu":
                return None
            if not _pre_cache_enabled():
                # the autotune point IS the first steady-state (pinned
                # precompute) call; with the pre-cache off it would
                # never fire, so don't pay the plan for a dead path
                return None
        elif mode != "1":
            return None
        n_b = int(self._tensors["pod_ns_id"].shape[0])
        if n_b < 2 * SLAB_BS:
            return None
        # upper gate: the slabs are materialized [q, n_tiles, w, N] HBM
        # copies (see verdict_counts_pallas_slab's design note); past
        # ~150k pods their bytes explode quadratically-in-tiles and the
        # chunked kernels win.  Budget both directions at 2 port cases
        # (at the widest ladder rung; a narrower chosen w only shrinks).
        n_tiles = -(-n_b // SLAB_BS) + -(-n_b // SLAB_BD)
        # slab_w_aug: the kernel augments each window with the OR-term
        # row and pads to the dtype sublane tile.  The slabs materialize
        # in the OPERAND dtype, so the budget is elements * itemsize —
        # counting elements as bytes let bf16 slabs blow 2x past
        # CYCLONUS_SLAB_MAX_BYTES
        itemsize = 2 if _resolve_operand_dtype(None) == "bf16" else 1
        bytes_per_case = n_tiles * slab_w_aug() * n_b * itemsize
        from ..utils import envflags

        budget = envflags.get_int("CYCLONUS_SLAB_MAX_BYTES")
        # the class-compression gather/index tensors share the budget:
        # without counting them here the slab + aux could jointly
        # over-commit HBM exactly when compression is supposed to save it
        aux = self._class_aux_bytes()
        # watermark gauges: planned slab HBM (q=2 budget point) vs the
        # budget — set before the gate so a rejected plan is visible too
        ti.SLAB_HBM_BYTES.set(2 * bytes_per_case + aux)
        ti.SLAB_HBM_BUDGET_BYTES.set(budget)
        if 2 * bytes_per_case + aux > budget:
            return None
        self._slab_bytes_per_case = bytes_per_case
        self._slab_budget = budget
        import jax

        n = self.encoding.cluster.n_pods
        if self._selpod_prebucket is not None:
            # pad the compaction-time pass to the bucketed axes: pad
            # selector rows match nothing; pad pod columns diverge from
            # the device (empty selectors match pads there) but every
            # pad column is force-masked below, so False is safe
            pre = self._selpod_prebucket
            selpod = np.zeros(
                (self._tensors["sel_req_kv"].shape[0], n_b), dtype=bool
            )
            selpod[: pre.shape[0], : pre.shape[1]] = pre
        else:
            selpod = _selector_pod_matches_host(self._tensors)
        pod_ns = self._tensors["pod_ns_id"]
        # adaptive window width: the kernel is MXU-MAC-bound (r5
        # triangulation), so contract over the NARROWEST ladder rung
        # whose windows cover every tile's band in both directions —
        # target bands at the bench shape are ~5-10 rows, far below the
        # conservative SLAB_W.  Wider-w correctness is monotone (rows
        # outside a tile's band are zero for its columns), so one shared
        # w = the max of the two directions' smallest fits.
        # rungs never exceed the configured SLAB_W ceiling (tests set it
        # low to drive the gate-rejection path)
        ladder = sorted({max(1, SLAB_W // 4), max(1, SLAB_W // 2), SLAB_W})
        plan = {}
        w_need = ladder[0]
        for direction, tile in (("egress", SLAB_BS), ("ingress", SLAB_BD)):
            d = self._tensors[direction]
            tm = d["target_ns"][:, None] == pod_ns[None, :]
            if selpod.size and d["target_sel"].size:
                t_sel = np.clip(d["target_sel"], 0, selpod.shape[0] - 1)
                tm &= selpod[t_sel]
            tm = tm[:, perm]
            tm[:, n:] = False  # pads sort last; mirrors the kernel's mask
            t0 = ok = None
            for w_try in ladder:
                t0, ok = slab_windows(tm, tile, w_try)
                if ok:
                    w_need = max(w_need, w_try)
                    break
            if not ok:
                return None
            plan[direction] = jax.device_put(t0)
        plan["w"] = w_need
        if mode == "1":
            # forced mode skips the autotune; set the choice only now
            # that the plan is actually accepted (a stale True with no
            # plan would break the invariant autotune readers rely on)
            with self._slab_lock:
                self._slab_choice = True
                self._kernel_choice = {"kernel": "slab"}
        return plan

    def _drain_autotune_orphan(self) -> None:
        """After an autotune timeout the abandoned daemon thread can
        still hold one in-flight compile+execution on the same backend.
        Before the next dispatch, wait briefly for it to finish (first
        call only; waiting forever would turn the contained candidate
        failure into the very stall it guards against).  Every dispatch
        that proceeds while the orphan is still live is counted in the
        autotune telemetry, so a polluted timing is recognizable."""
        orphan = self._autotune_orphan
        if orphan is None:
            return
        import os

        timeout = (
            0.0
            if orphan["waited"]
            else float(os.environ.get("CYCLONUS_AUTOTUNE_DRAIN_S", "5"))
        )
        orphan["waited"] = True
        if orphan["event"].wait(timeout):
            self._autotune_orphan = None
            return
        if self._slab_autotune is not None:
            self._slab_autotune["orphan_overlap_dispatches"] = (
                self._slab_autotune.get("orphan_overlap_dispatches", 0) + 1
            )

    def _autotune_enabled(self) -> bool:
        """CYCLONUS_AUTOTUNE: "auto" (default — tune on TPU, where the
        timings mean something), "1" (force: how CPU tests exercise the
        search/persistence machinery in interpret mode), "0" (off)."""
        import os

        mode = os.environ.get("CYCLONUS_AUTOTUNE", "auto").lower()
        if mode == "0":
            return False
        if mode == "1":
            return True
        import jax

        return jax.default_backend() == "tpu"

    def _autotune_key(self, q: int) -> str:
        """Persisted-cache key: (shape bucket, mesh, dtype plan) — see
        engine/autotune.py for why exactly these dimensions make a
        winner transferable across processes."""
        import jax

        from . import autotune as at
        from .pallas_kernel import _resolve_operand_dtype

        t = self._tensors
        shape = {
            "n": int(t["pod_ns_id"].shape[0]),
            "te": int(t["egress"]["target_ns"].shape[0]),
            "ti": int(t["ingress"]["target_ns"].shape[0]),
            "q": int(q),
            "tiered": self.tiers is not None,
            "classes": self._class_state is not None,
        }
        devs = jax.devices()
        mesh = (
            f"{jax.default_backend()}:{devs[0].device_kind}:{len(devs)}"
        )
        dtype = "packed32" if self._pack else _resolve_operand_dtype(None)
        return at.make_key(shape, mesh, dtype)

    def _timed_rounds(self, dispatch, cancelled=None):
        """(best_s, round_times, out): min-of-N pipelined timing.  Each
        round issues CYCLONUS_AUTOTUNE_REPS async dispatches with ONE
        value readback as the barrier (block_until_ready can return
        optimistically over a tunneled device); the candidate keeps the
        MIN over CYCLONUS_AUTOTUNE_ROUNDS rounds — the same min-of-N
        discipline the bench and the overhead tests use, because a
        single-shot comparison under tunnel jitter can pick the loser
        (the r5 flip this replaces)."""
        import os
        import time as _time

        out = dispatch()
        np.asarray(out)  # compile + first execution outside the timing
        reps = max(1, int(os.environ.get("CYCLONUS_AUTOTUNE_REPS", "4")))
        rounds = max(1, int(os.environ.get("CYCLONUS_AUTOTUNE_ROUNDS", "3")))
        times = []
        for _ in range(rounds):
            t0 = _time.perf_counter()
            outs = []
            for _ in range(reps):
                if cancelled is not None and cancelled["v"]:
                    raise RuntimeError("autotune candidate cancelled")
                outs.append(dispatch())
            np.asarray(outs[-1])  # in-order stream: one barrier covers all
            times.append((_time.perf_counter() - t0) / reps)
        return min(times), times, out

    @staticmethod
    def _noise_floor(baseline_rounds) -> float:
        """The margin a challenger must beat the incumbent by: at least
        10%, widened to the incumbent's own observed round-to-round
        spread (capped at 50%) — if the baseline wobbles 30% between
        rounds, a 12% 'win' is noise, not signal."""
        lo = min(baseline_rounds)
        hi = max(baseline_rounds)
        spread = (hi - lo) / max(lo, 1e-9)
        return max(0.10, min(0.5, spread))

    def _autotune_slab(self, n32, key):
        """Steady-state kernel autotune for the DENSE (CYCLONUS_PACK=0)
        dtype plan: time the default and the slab counts programs from
        the SAME pinned precompute and keep the winner for the rest of
        the engine's life — min-of-N rounds per leg (_timed_rounds)
        with a noise-floor margin (_noise_floor), the winner persisted
        via engine/autotune.py and ADOPTED search-free by the next
        process with the same (shape bucket, mesh, dtype plan).  The
        candidate is the slab kernel dispatched FROM CACHED OPERANDS
        (_slab_ops_for): the one-time gather build happens inside the
        bounded candidate leg but outside its timed loop, so the
        comparison is steady state vs steady state.  Returns the
        winner's partials for the call that paid for the tuning."""
        import logging
        import time as _time

        from . import autotune as at

        q = len(key[0]) // 4  # key[0] is q_port.tobytes() (int32)
        akey = self._autotune_key(q)
        persisted = at.load_winner(akey)
        if persisted is not None and persisted.get("kernel") in (
            "slab",
            "default",
        ):
            chose_slab = persisted["kernel"] == "slab"
            with self._slab_lock:
                self._slab_choice = chose_slab
                self._kernel_choice = {"kernel": persisted["kernel"]}
                if not chose_slab:
                    self._slab_ops_cache = None
            ti.AUTOTUNE_CACHE.inc(outcome="hit")
            self._autotune_stats = {
                "source": "cache",
                "winner": dict(persisted),
                "search_s": 0.0,
                "candidates": [],
            }
            if chose_slab:
                return self._counts_from_slab_ops_jit(self._slab_ops_for(key))
            return self._counts_from_pre_jit(
                self._pre_cache[1], n32, None, None
            )
        if at.cache_path() is not None:
            ti.AUTOTUNE_CACHE.inc(outcome="miss")
        ti.AUTOTUNE_SEARCHES.inc()
        t_search0 = _time.perf_counter()

        pre = self._pre_cache[1]
        cancelled = {"v": False}

        t_default, rounds_default, out_default = self._timed_rounds(
            lambda: self._counts_from_pre_jit(pre, n32, None, None),
            cancelled,
        )
        # the candidate leg is BOUNDED as well as caught: its first call
        # compiles a brand-new program, and a wedged remote compile
        # service (the known >=1M-pod pathology) must reject the
        # candidate, not stall the caller into a watchdog kill.  On
        # timeout the abandoned daemon thread finishes its in-flight
        # compile+execution plus up to reps-1 already-queued pipelined
        # executions (~0.1 s each; the async dispatches enqueue within
        # milliseconds, so the cancel flag rarely interrupts the loop) —
        # the orphan gate (_drain_autotune_orphan) bounds and counts any
        # overlap with the caller's subsequent default-path work.
        import threading

        from ..utils import envflags
        from ..utils.bounded import run_bounded

        timeout_s = envflags.get_float("CYCLONUS_AUTOTUNE_TIMEOUT_S")
        candidate_done = threading.Event()

        def candidate():
            try:
                # the one-time gather build (a fresh program of its own)
                # is bounded here but excluded from the timed loop
                ops = self._slab_ops_for(key)
                return self._timed_rounds(
                    lambda: self._counts_from_slab_ops_jit(ops), cancelled
                )
            finally:
                candidate_done.set()

        status, value = run_bounded(candidate, timeout_s)
        if status != "ok":
            cancelled["v"] = True
            # compile/run failure or timeout: the candidate rejects
            # itself — it must never take down the proven default path
            # (this autotune is the only place the slab program runs
            # unforced, so the failure is contained here).  Rejection and
            # cache clear happen atomically under _slab_lock: the
            # abandoned thread may still be inside _slab_ops_for, and an
            # unguarded clear here could be overwritten by its cache
            # fill, re-pinning slab HBM for a rejected kernel
            with self._slab_lock:
                self._slab_choice = False
                self._kernel_choice = {"kernel": "default"}
                self._slab_ops_cache = None
            # the rejection is telemetry too: BENCH detail must show WHY
            # there are no timed legs, and whether the abandoned thread's
            # in-flight work later raced a real dispatch
            self._slab_autotune = {
                "default_s": round(t_default, 4),
                "candidate": status,
                "candidate_error": None if status == "timeout" else repr(value),
                "orphan_overlap_dispatches": 0,
            }
            self._autotune_stats = {
                "source": "search",
                "winner": {"kernel": "default"},
                "search_s": round(_time.perf_counter() - t_search0, 4),
                "candidates": [
                    {"kernel": "default", "s": round(t_default, 4)},
                    {"kernel": "slab", "status": status},
                ],
            }
            ti.AUTOTUNE_OUTCOMES.inc(outcome=status)
            if status == "timeout":
                # the abandoned daemon thread may still hold one in-flight
                # compile+execution; gate the NEXT dispatch on it so a
                # spurious slab execution cannot silently pollute the
                # default path's first timed leg (_drain_autotune_orphan)
                self._autotune_orphan = {
                    "event": candidate_done, "waited": False
                }
            logging.getLogger(__name__).warning(
                "slab autotune: candidate %s (%s) -> default",
                "timed out" if status == "timeout" else "failed",
                f"{timeout_s:g}s" if status == "timeout" else repr(value),
            )
            return out_default
        t_slab, rounds_slab, out_slab = value
        # min-of-N verdict with a noise floor: the slab must beat the
        # default by MORE than the default's own observed jitter (at
        # least the historical 10% margin) — the single-shot comparison
        # this replaces could pick the loser under tunnel noise
        floor = self._noise_floor(rounds_default)
        chose_slab = bool(t_slab < (1.0 - floor) * t_default)
        with self._slab_lock:
            self._slab_choice = chose_slab
            self._kernel_choice = {
                "kernel": "slab" if chose_slab else "default"
            }
            if not chose_slab:
                # a timing-rejected slab never dispatches again: its
                # cached operands (up to the slab byte budget of HBM)
                # must not stay pinned next to the precompute
                self._slab_ops_cache = None
        search_s = _time.perf_counter() - t_search0
        self._slab_autotune = {
            "default_s": round(t_default, 4),
            "slab_s": round(t_slab, 4),
            "noise_floor": round(floor, 4),
        }
        winner = {"kernel": "slab" if chose_slab else "default"}
        self._autotune_stats = {
            "source": "search",
            "winner": winner,
            "search_s": round(search_s, 4),
            "noise_floor": round(floor, 4),
            "candidates": [
                {"kernel": "default", "s": round(t_default, 4)},
                {"kernel": "slab", "s": round(t_slab, 4)},
            ],
        }
        if at.store_winner(
            akey,
            winner,
            {"default_s": t_default, "slab_s": t_slab},
        ):
            ti.AUTOTUNE_CACHE.inc(outcome="store")
        ti.AUTOTUNE_OUTCOMES.inc(
            outcome="slab" if chose_slab else "default"
        )
        logging.getLogger(__name__).info(
            "slab autotune: default %.4fs, slab %.4fs (floor %.0f%%) -> %s",
            t_default,
            t_slab,
            floor * 100,
            "slab" if chose_slab else "default",
        )
        return out_slab if chose_slab else out_default

    def _autotune_packed(self, n32, key, q: int):
        """Steady-state tile autotune for the PACKED dtype plan: the
        candidates are the packed kernel at every eligible (bs, bd) of
        pallas_kernel.PACKED_TILE_CANDIDATES, enumerated per shape
        bucket, timed min-of-N from the SAME pinned precompute, the
        winner adopted for the engine's life AND persisted keyed by
        (shape bucket, mesh, dtype plan) — a restarted process adopts
        it with zero candidate search (the AUTOTUNE_SEARCHES counter
        stays flat; asserted by tests/test_engine_packed.py).  Returns
        the winner's partials for the call that paid for the tuning."""
        import logging
        import os
        import time as _time

        from ..utils.bounded import run_bounded
        from . import autotune as at
        from .pallas_kernel import PACKED_TILE_CANDIDATES

        n_b = int(self._tensors["pod_ns_id"].shape[0])
        cands = [PACKED_TILE_CANDIDATES[0]]
        for bs, bd in PACKED_TILE_CANDIDATES[1:]:
            # a tile taller than the problem only adds padding; the
            # int32 partial-count bound re-checks like _tiles_for
            if n_b > bs and bs * max(n_b, bd) < 2**31:
                cands.append((bs, bd))

        def adopt(bs, bd):
            choice = {"kernel": "packed", "bs": int(bs), "bd": int(bd)}
            with self._slab_lock:
                self._kernel_choice = choice
                self._slab_choice = False
            return choice

        akey = self._autotune_key(q)
        pre = self._pre_cache[1]
        persisted = at.load_winner(akey)
        if (
            persisted is not None
            and persisted.get("kernel") == "packed"
            and (persisted.get("bs"), persisted.get("bd")) in cands
        ):
            choice = adopt(persisted["bs"], persisted["bd"])
            ti.AUTOTUNE_CACHE.inc(outcome="hit")
            self._autotune_stats = {
                "source": "cache",
                "winner": choice,
                "search_s": 0.0,
                "candidates": [],
            }
            return self._counts_from_pre_packed_jit(
                pre, n32, bs=choice["bs"], bd=choice["bd"]
            )
        if at.cache_path() is not None:
            ti.AUTOTUNE_CACHE.inc(outcome="miss")
        if len(cands) == 1:
            # one eligible tile: nothing to search, nothing to persist
            choice = adopt(*cands[0])
            self._autotune_stats = {
                "source": "single",
                "winner": choice,
                "search_s": 0.0,
                "candidates": [
                    {"kernel": "packed", "bs": cands[0][0], "bd": cands[0][1]}
                ],
            }
            return self._counts_from_pre_packed_jit(
                pre, n32, bs=cands[0][0], bd=cands[0][1]
            )

        ti.AUTOTUNE_SEARCHES.inc()
        t_search0 = _time.perf_counter()
        from ..utils import envflags

        timeout_s = envflags.get_float("CYCLONUS_AUTOTUNE_TIMEOUT_S")
        results = []  # (bs, bd, best_s, rounds, out) for candidates that ran
        stats = []
        base_rounds = None
        for idx, (bs, bd) in enumerate(cands):
            def leg(_bs=bs, _bd=bd):
                return self._timed_rounds(
                    lambda: self._counts_from_pre_packed_jit(
                        pre, n32, bs=_bs, bd=_bd
                    )
                )

            if idx == 0:
                # the default tile is the proven configuration: timed
                # unbounded (it is also the fallback on any failure)
                best, rounds, out = leg()
                base_rounds = rounds
                results.append((bs, bd, best, out))
                stats.append(
                    {"kernel": "packed", "bs": bs, "bd": bd,
                     "s": round(best, 4)}
                )
                continue
            # every challenger compiles a fresh program: bounded so a
            # wedged remote compile rejects the CANDIDATE, not the run
            status, value = run_bounded(leg, timeout_s)
            if status == "ok":
                best, rounds, out = value
                results.append((bs, bd, best, out))
                stats.append(
                    {"kernel": "packed", "bs": bs, "bd": bd,
                     "s": round(best, 4)}
                )
            else:
                stats.append(
                    {"kernel": "packed", "bs": bs, "bd": bd,
                     "status": status}
                )
                ti.AUTOTUNE_OUTCOMES.inc(outcome=status)

        # min-of-N winner, noise-floored against the default tile: a
        # challenger must beat it by more than its own observed jitter
        floor = self._noise_floor(base_rounds)
        d_bs, d_bd, t_default, out_default = results[0]
        winner = (d_bs, d_bd, t_default, out_default)
        for bs, bd, best, out in results[1:]:
            if best < (1.0 - floor) * winner[2]:
                winner = (bs, bd, best, out)
        choice = adopt(winner[0], winner[1])
        search_s = _time.perf_counter() - t_search0
        self._autotune_stats = {
            "source": "search",
            "winner": choice,
            "search_s": round(search_s, 4),
            "noise_floor": round(floor, 4),
            "candidates": stats,
        }
        if at.store_winner(
            akey, choice, {c.get("bs", 0): c.get("s") for c in stats}
        ):
            ti.AUTOTUNE_CACHE.inc(outcome="store")
        ti.AUTOTUNE_OUTCOMES.inc(outcome="packed")
        logging.getLogger(__name__).info(
            "packed autotune: %d candidates in %.2fs -> tile (%d, %d)",
            len(cands),
            search_s,
            winner[0],
            winner[1],
        )
        return winner[3]

    def pack_stats(self) -> Dict:
        """The bit-packed-plan summary bench.py records as detail.pack
        on every line: whether the packed dtype plan is active, the
        packed word depths (kt twin), the tuned winner, and the
        autotune forensics (search time, candidates tried, cache
        source)."""
        from . import autotune as at
        from .pallas_kernel import _resolve_operand_dtype

        with self._slab_lock:
            choice = self._kernel_choice
        t = self._tensors
        return {
            "active": self._pack,
            "dtype": "packed32" if self._pack else _resolve_operand_dtype(None),
            "words": [
                packed_words(int(t["egress"]["target_ns"].shape[0])),
                packed_words(int(t["ingress"]["target_ns"].shape[0])),
            ],
            "winner": dict(choice) if choice else None,
            "autotune": self._autotune_stats,
            "cache_path": at.cache_path(),
        }

    def _build_counts_jits(self) -> None:
        """Build the three counts programs once per engine: the fused
        cold-path jit (unpack + sort + precompute + pallas in one
        program), and the split pair (_pre_jit / _counts_from_pre_jit)
        the repeat path uses to keep the precompute device-resident."""
        import jax

        from .pallas_kernel import (
            _should_interpret,
            slab_operands,
            verdict_counts_pallas,
            verdict_counts_pallas_packed,
            verdict_counts_pallas_slab,
            verdict_counts_pallas_slab_from_ops,
        )
        from .sharded import _POD_KEYS
        from .tiled import _precompute

        unpack = self._unpack
        interpret = _should_interpret()
        pack = self._pack

        def prepared_tensors(buf, perm, q_port, q_name, q_proto):
            import jax.numpy as jnp

            tensors = dict(unpack(buf))
            for k in _POD_KEYS:
                tensors[k] = jnp.take(tensors[k], perm, axis=0)
            for direction in ("ingress", "egress"):
                if "host_ip_match" in tensors[direction]:
                    d = dict(tensors[direction])
                    d["host_ip_match"] = jnp.take(
                        d["host_ip_match"], perm, axis=1
                    )
                    tensors[direction] = d
            tensors["q_port"] = q_port
            tensors["q_name"] = q_name
            tensors["q_proto"] = q_proto
            return tensors

        def packed_tier(pre):
            e, ig = pre["egress"], pre["ingress"]
            if "tier" not in e:
                return None
            return {"egress": e["tier"], "ingress": ig["tier"]}

        def counts_from_pre_packed(pre, n_pods, bs, bd):
            e, ig = pre["egress"], pre["ingress"]
            return verdict_counts_pallas_packed(
                e["tmatch_pk"], e["has_target"], e["tallow_pk"],
                ig["tmatch_pk"], ig["has_target"], ig["tallow_pk"],
                n_pods=n_pods, tier=packed_tier(pre),
                bs=bs, bd=bd, interpret=interpret,
            )

        def counts_from_pre(pre, n_pods, t0_e=None, t0_i=None):
            e, ig = pre["egress"], pre["ingress"]
            if "tallow_pk" in e:
                # packed dtype plan: the packed kernel at the DEFAULT
                # tile (the tuned-tile steady state dispatches through
                # _counts_from_pre_packed_jit instead); the fused tier
                # epilogue rides when the engine is tiered
                from .pallas_kernel import PACKED_BD, PACKED_BS

                return counts_from_pre_packed(
                    pre, n_pods, PACKED_BS, PACKED_BD
                )
            if t0_e is not None:
                # per-tile slab fast path (host-verified eligibility)
                return verdict_counts_pallas_slab(
                    e["tmatch"], e["has_target"], e["tallow_bf"],
                    ig["tmatch"], ig["has_target"], ig["tallow_bf"],
                    t0_e, t0_i, n_pods, interpret=interpret,
                )
            return verdict_counts_pallas(
                e["tmatch"],
                e["has_target"],
                e["tallow_bf"],
                ig["tmatch"],
                ig["has_target"],
                ig["tallow_bf"],
                n_pods=n_pods,
                interpret=interpret,
            )

        @jax.jit
        def counts_packed(buf, perm, q_port, q_name, q_proto, n_pods, t0_e=None, t0_i=None):
            pre = _precompute(
                prepared_tensors(buf, perm, q_port, q_name, q_proto), pack
            )
            return counts_from_pre(pre, n_pods, t0_e, t0_i)

        # every program below rides the persistent AOT executable cache
        # (engine/aot_cache.py): a restarted process adopts serialized
        # executables — zero trace, zero compile — and any program the
        # runtime can't serialize falls back to the plain jit.  The
        # fused/pre programs bake the unpack closure's leaf layout into
        # their trace, so their cache key carries the metas digest.
        unpack_plan = self._aot_plan(self._metas_digest(unpack))
        self._counts_packed_jit = aot_cache.AotProgram(
            "counts.fused", counts_packed, plan=unpack_plan
        )
        self._pre_jit = aot_cache.AotProgram(
            "counts.pre",
            jax.jit(
                lambda buf, perm, qp, qn, qr: _precompute(
                    prepared_tensors(buf, perm, qp, qn, qr), pack
                )
            ),
            plan=unpack_plan,
        )
        self._counts_from_pre_jit = aot_cache.AotProgram(
            "counts.from_pre", jax.jit(counts_from_pre), plan=self._aot_plan()
        )
        self._counts_from_pre_packed_jit = aot_cache.AotProgram(
            "counts.from_pre_packed",
            jax.jit(counts_from_pre_packed, static_argnames=("bs", "bd")),
            plan=self._aot_plan(),
            static_argnames=("bs", "bd"),
        )

        def slab_ops(pre, n_pods, t0_e, t0_i, w=None):
            e, ig = pre["egress"], pre["ingress"]
            return slab_operands(
                e["tmatch"], e["has_target"], e["tallow_bf"],
                ig["tmatch"], ig["has_target"], ig["tallow_bf"],
                t0_e, t0_i, n_pods, w=w,
            )

        self._slab_ops_jit = jax.jit(slab_ops, static_argnames=("w",))
        self._counts_from_slab_ops_jit = jax.jit(
            lambda ops: verdict_counts_pallas_slab_from_ops(
                ops, interpret=interpret
            )
        )
        ti.ENGINE_PROGRAMS_BUILT.inc()

    def _counts_pallas_packed(self, cases: Sequence[PortCase], n: int) -> Dict[str, int]:
        """Telemetry shell around the pallas counts path: one flight-
        recorder entry + latency/throughput instruments per evaluation
        (host-side only — the timed body below never syncs for it)."""
        with ti.eval_flight("counts.pallas", n, len(cases)) as fl:
            counts = self._counts_pallas_dispatch(cases, n, fl)
            fl.set(cells=counts["cells"])
            return counts

    def _counts_pallas_dispatch(
        self, cases: Sequence[PortCase], n: int, fl
    ) -> Dict[str, int]:
        """The fused pallas counts path over the SINGLE-BUFFER tensor
        transfer: unpack + pod-axis ns-sort + precompute + pallas counts
        all trace into one jit, so a cold process pays one host->device
        transfer (shared with the grid/pairs paths), one trace, one
        (persistently cached) compile, and one execution.  Records as
        planspec path "counts.pallas"; the steady-state kernel choice
        within it records its own counts.steady.* leaf.

        Why the sort: a target applies to pods of exactly one namespace,
        so with pods ns-sorted (on device, via the permutation gather
        below) and targets ns-sorted (in the base tensors —
        _sort_targets_by_ns) the tmatch matrices become near block
        diagonal and most (pod-tile, target-chunk) blocks are ALL ZERO;
        the pallas kernel skips their matmuls (scalar-prefetch nz maps),
        dropping the dominant flops term from O(N^2 T) dense to the
        occupied blocks only.  Counts are invariant under both
        permutations, so only this path sorts; grid paths keep caller
        order."""
        import jax

        from .sharded import _POD_KEYS

        planspec.record("counts.pallas")
        buf = self._ensure_packed()
        if self._pod_perm_dev is None:
            # bucketing pads carry ns id -1: keep them LAST (the kernel's
            # validity mask assumes real pods occupy the first n rows)
            ns = self._tensors["pod_ns_id"]
            key = np.where(ns < 0, np.iinfo(np.int32).max, ns)
            perm = np.argsort(key, kind="stable").astype(np.int32)
            self._pod_perm_host = perm
            with phase("engine.device_put"):
                self._pod_perm_dev = jax.device_put(perm)
        if self._slab_plan_state == "unset":
            with phase("engine.slab_plan"):
                self._slab_plan_state = self._slab_plan(self._pod_perm_host)
        slab = self._slab_plan_state
        if self._counts_packed_jit is None:
            self._build_counts_jits()
        self._drain_autotune_orphan()
        from .pallas_kernel import sum_partials

        key, slab_ok, slab_args, (q_port, q_name, q_proto), choice = (
            self._steady_state_args(cases)
        )
        t_dispatch = time.perf_counter()
        autotuned = False
        if self._pre_cache is not None and self._pre_cache[0] == key:
            # steady state: only the pallas counts kernel runs
            self._pre_cache_misses = 0
            ti.PRE_CACHE_HITS.inc()
            fl.set(mode="steady", slab=slab_args[0] is not None)
            # CYCLONUS_AUTOTUNE gates BOTH plans (the dense slab search
            # costs the same timed rounds and cache writes the packed
            # search does); the dense plan additionally needs an
            # eligible slab plan to have anything to race
            tune_pending = (
                choice is None
                and self._autotune_enabled()
                and (self._pack or slab_ok)
            )
            if tune_pending:
                autotuned = True
                # autotune at the first steady-state call: every
                # candidate runs from the SAME pinned precompute, so
                # this times exactly what every later call will execute
                # (or adopts the persisted winner with no search at all)
                with phase("engine.autotune"):
                    if self._pack:
                        partials = self._autotune_packed(
                            np.int32(n), key, len(cases)
                        )
                    else:
                        partials = self._autotune_slab(np.int32(n), key)
            else:
                with phase("engine.dispatch"):
                    partials = self._dispatch_steady(key, slab_args, choice)
        elif (
            self._last_counts_key == key
            and key != self._pre_cache_declined
            and _pre_cache_enabled()
            and self._pre_bytes_estimate(len(cases)) <= _PRE_CACHE_MAX_BYTES
        ):
            # second consecutive evaluation of the same case set: switch
            # to the split path and keep the precompute device-resident.
            # The split programs compile once (persistently cached); the
            # cold first call keeps the single fused compile.
            ti.PRE_CACHE_MISSES.inc()
            ti.PRE_CACHE_BUDGET_BYTES.set(_PRE_CACHE_MAX_BYTES)
            fl.set(mode="split")
            with phase("engine.dispatch"):
                pre = self._pre_jit(
                    buf, self._pod_perm_dev, q_port, q_name, q_proto
                )
                nbytes = sum(
                    x.nbytes for x in jax.tree_util.tree_leaves(pre)
                )
                if nbytes <= _PRE_CACHE_MAX_BYTES:
                    self._pre_cache = (key, pre)  # evicts any other set
                    with self._slab_lock:
                        self._slab_ops_cache = None  # stale for new set
                    self._pre_cache_misses = 0
                    ti.PRE_CACHE_BYTES.set(nbytes)
                else:
                    # too big to pin: remember, so repeats go back to the
                    # single fused dispatch instead of this split path
                    self._pre_cache_declined = key
                # always the DEFAULT program here: with the slab chosen,
                # the steady state dispatches from cached operands
                # (_dispatch_steady), so a split-path slab trace would be
                # a heavy one-off compile used exactly once
                partials = self._counts_from_pre_jit(
                    pre, np.int32(n), None, None
                )
        else:
            self._last_counts_key = key
            ti.PRE_CACHE_MISSES.inc()
            fl.set(mode="fused")
            if self._pre_cache is not None:
                # release the cached set's HBM only after two consecutive
                # other-set evaluations: a single interleaved call (the
                # A, B, A, B probe pattern) must not thrash the cache
                self._pre_cache_misses += 1
                if self._pre_cache_misses >= 2:
                    self._pre_cache = None
                    with self._slab_lock:
                        self._slab_ops_cache = None  # HBM goes with the pre
                    ti.PRE_CACHE_BYTES.set(0)
            with phase("engine.dispatch"):
                partials = self._counts_packed_jit(
                    buf, self._pod_perm_dev, q_port, q_name, q_proto,
                    np.int32(n), *slab_args,
                )
        if not autotuned:
            # the autotune branch runs synchronous timed executions of
            # both candidate programs — recording that window as "async
            # dispatch" would poison the dispatch-vs-device split
            ti.EVAL_DISPATCH_SECONDS.set(time.perf_counter() - t_dispatch)
        # the [Q, n_tiles, 3] readback is the execution barrier: device
        # run time (and, on a remote-attached chip, any service-side
        # stall) lands here, not in the async dispatch above
        t_execute = time.perf_counter()
        with phase("engine.execute"):
            partials = np.asarray(partials)
        ti.EVAL_EXECUTE_SECONDS.set(time.perf_counter() - t_execute)
        return sum_partials(partials, len(cases), n)

    def _steady_state_args(self, cases: Sequence[PortCase]):
        """(key, slab_ok, slab_args, (q_port, q_name, q_proto), choice)
        for the pinned-precompute steady state — THE single definition
        of which program a steady-state dispatch runs, shared by
        evaluate_grid_counts and counts_pipelined_eval_s so the two can
        never measure different programs.  `choice` is the tuned
        _kernel_choice dict (None until the autotune or a persisted
        adoption resolves it), read ONCE under _slab_lock so callers
        branch on one coherent value instead of re-reading an attribute
        the autotune's abandoned candidate thread may be racing.
        slab_args engages only when a plan exists, the autotune chose
        the slab kernel, AND the slab's materialized HBM bytes fit the
        budget at THIS case count (plan time budgets q=2 — a larger
        case list must fall back to the default kernel, not OOM the
        device)."""
        q_port, q_name, q_proto = self._port_case_arrays(cases)
        n = self.encoding.cluster.n_pods
        key = (q_port.tobytes(), q_name.tobytes(), q_proto.tobytes(), n)
        slab = self._slab_plan_state
        slab_ok = isinstance(slab, dict) and (
            self._slab_bytes_per_case is None
            or len(cases) * self._slab_bytes_per_case
            + self._class_aux_bytes()
            <= self._slab_budget
        )
        with self._slab_lock:
            choice = self._kernel_choice
        slab_args = (
            (slab["egress"], slab["ingress"])
            if slab_ok and choice is not None and choice.get("kernel") == "slab"
            else (None, None)
        )
        return key, slab_ok, slab_args, (q_port, q_name, q_proto), choice

    def _slab_ops_for(self, key):
        """Device-resident gathered slab operands for the pinned case
        set, built ONCE per (case set, plan) and cached next to the
        pre-cache (evicted together).  The HBM held is bounded by the
        same CYCLONUS_SLAB_MAX_BYTES budget that gates the slab path —
        pinning holds the SAME bytes a per-dispatch rebuild would
        transiently allocate, trading that rebuild (measured at more
        than the depth cut's savings, r5) for residency."""
        # one locked read of the (key, ops) tuple: the old
        # `self._slab_ops_cache is not None and self._slab_ops_cache[0]`
        # double read could interleave with the autotune rejection's
        # clear and crash on None[0] (found by tools/locklint.py LK001;
        # the schedule is fuzzed by tests/raceharness.py)
        with self._slab_lock:
            cached = self._slab_ops_cache
        if cached is not None and cached[0] == key:
            ti.SLAB_OPS_CACHE_HITS.inc()
            return cached[1]
        ti.SLAB_OPS_CACHE_MISSES.inc()
        slab = self._slab_plan_state
        n32 = np.int32(self.encoding.cluster.n_pods)
        # snapshot _pre_cache ONCE: the issuing thread guarantees it is
        # pinned before calling here, but the abandoned autotune thread
        # has no such guarantee — the issuing thread's 2-miss eviction
        # can null it mid-build, and a direct self._pre_cache[1] read
        # would crash on None[1].  The raise is a contained candidate
        # failure (run_bounded catches it and the autotune rejects).
        pre_cache = self._pre_cache
        if pre_cache is None:
            raise RuntimeError(
                "slab operand build raced pre-cache eviction "
                "(abandoned autotune candidate; contained)"
            )
        ops = self._slab_ops_jit(
            pre_cache[1], n32, slab["egress"], slab["ingress"],
            w=slab.get("w"),
        )
        # the ACTUAL pinned bytes supersede the plan-time q=2 estimate
        # (.nbytes is a host-side attribute: no device sync)
        import jax as _jax

        ti.SLAB_HBM_BYTES.set(
            sum(x.nbytes for x in _jax.tree_util.tree_leaves(ops))
        )
        # check-and-fill under the SAME lock as the autotune's rejection
        # writes: without it an abandoned candidate thread can pass the
        # choice check, lose the CPU to the main thread's rejection +
        # cache clear, then re-pin slab HBM for the rejected kernel
        with self._slab_lock:
            if self._slab_choice is False:
                return ops
            self._slab_ops_cache = (key, ops)
        return ops

    def _dispatch_steady(self, key, slab_args, choice=None):
        """One steady-state dispatch of the CHOSEN program: the slab
        kernel from the cached gathered operands, the packed kernel at
        the tuned tile, or the default program from the pinned
        precompute (which under the packed plan is the packed kernel at
        the default tile).  Returns the async partials array."""
        if slab_args[0] is not None:
            planspec.record("counts.steady.slab")
            return self._counts_from_slab_ops_jit(self._slab_ops_for(key))
        n32 = np.int32(self.encoding.cluster.n_pods)
        if (
            choice is not None
            and choice.get("kernel") == "packed"
            and "bs" in choice
        ):
            planspec.record("counts.steady.packed_tuned")
            return self._counts_from_pre_packed_jit(
                self._pre_cache[1], n32, bs=choice["bs"], bd=choice["bd"]
            )
        planspec.record("counts.steady.default")
        return self._counts_from_pre_jit(self._pre_cache[1], n32, None, None)

    def counts_pipelined_eval_s(
        self, cases: Sequence[PortCase], reps: int = 10
    ):
        """Steady-state DEVICE-side seconds per counts evaluation:
        dispatch `reps` identical programs back-to-back from the pinned
        precompute and read back only the last, so the device queue
        pipelines and the per-eval cost excludes the per-dispatch
        host->device->host round trip a sync eval pays (~0.09 s over a
        tunneled chip — more than the kernel itself at the 100k bench
        shape).  Runs exactly the program the steady state runs
        (_steady_state_args).  Returns (seconds_per_eval, counts) or
        None when the engine is not at the pinned-precompute steady
        state for this case set — or when a cancelled autotune
        candidate's execution is still in flight (it shares the device
        queue and would pollute a number recorded as stable)."""
        import time as _time

        if self._class_state is not None and self._class_counts_eligible(
            len(cases)
        ):
            # the orphan gate applies here too: a cancelled autotune
            # candidate (possible when an earlier INELIGIBLE case set
            # ran the dense pallas path) shares the device queue and
            # would pollute the compressed timing just the same
            self._drain_autotune_orphan()
            if self._autotune_orphan is not None:
                return None
            return self._pipelined_classes(cases, reps)
        key, _slab_ok, slab_args, _qs, choice = self._steady_state_args(cases)
        if self._pre_cache is None or self._pre_cache[0] != key:
            return None
        self._drain_autotune_orphan()
        if self._autotune_orphan is not None:
            return None
        n = self.encoding.cluster.n_pods
        out = self._dispatch_steady(key, slab_args, choice)
        np.asarray(out)  # warm barrier
        t0 = _time.perf_counter()
        outs = [
            self._dispatch_steady(key, slab_args, choice) for _ in range(reps)
        ]
        partials = np.asarray(outs[-1])  # in-order stream: one barrier
        dt = (_time.perf_counter() - t0) / reps
        from .pallas_kernel import sum_partials

        counts = sum_partials(partials, len(cases), n)
        # the pipelined rate as a REAL gauge: what a co-located or
        # batched caller sustains, vs the sync eval's dispatch-RTT-bound
        # number (the r5 gap this telemetry layer exists to expose)
        if dt > 0:
            ti.EVAL_DEVICE_SECONDS.set(dt)
            ti.EVAL_PIPELINED_CELLS_PER_SEC.set(counts["cells"] / dt)
        return dt, counts

    def evaluate_grid_counts_sharded(
        self,
        cases: Sequence[PortCase],
        block: int = 1024,
        mesh=None,
        kernel: str = None,
    ) -> Dict[str, int]:
        """Mesh-parallel tiled counts: source rows split over the mesh,
        per-device work, one all-gather of partials (engine/tiled.py).
        The multi-chip path for grids past one device's wall-clock.
        kernel="pallas" (the TPU default) runs the fused rectangular
        verdict+count kernel per device; kernel="xla" the tile loop."""
        self._check_ips()
        n = self.encoding.cluster.n_pods
        if not cases or n == 0:
            return {"ingress": 0, "egress": 0, "combined": 0, "cells": 0}
        if self._class_state is not None and self._class_counts_eligible(
            len(cases)
        ):
            return self._counts_classes(
                cases, n, sharded=True, block=block, mesh=mesh
            )
        from .tiled import evaluate_grid_counts_sharded

        # tiers x per-device pallas: same matrix cell discipline as
        # evaluate_grid_counts — auto routes to the XLA tile body (it
        # carries the tier resolution epilogue), an explicit pallas
        # request fails loudly with the declared message
        kernel = planspec.resolve_sharded_counts_kernel(
            kernel=kernel, tiers=self.tiers is not None
        )
        return evaluate_grid_counts_sharded(
            self._tensors_with_cases(cases), n, block=block, mesh=mesh,
            kernel=kernel,
        )

    def evaluate_grid_counts_ring(
        self, cases: Sequence[PortCase], block: int = 1024, mesh=None
    ) -> Dict[str, int]:
        """Ring-rotation counts: both pod axes stay sharded and the
        dst-side precompute rotates around the mesh with ppermute —
        per-device memory O(N / mesh size), the path for clusters whose
        precompute exceeds one device (engine/tiled.py)."""
        self._check_ips()
        n = self.encoding.cluster.n_pods
        if not cases or n == 0:
            return {"ingress": 0, "egress": 0, "combined": 0, "cells": 0}
        planspec.record("counts.ring")
        from .tiled import evaluate_grid_counts_ring

        return evaluate_grid_counts_ring(
            self._tensors_with_cases(cases), n, block=block, mesh=mesh
        )

    def mesh_counts_pipelined_eval_s(
        self,
        cases: Sequence[PortCase],
        reps: int = 10,
        block: int = 1024,
        mesh=None,
    ):
        """Steady-state DEVICE-side seconds per MESH counts evaluation —
        counts_pipelined_eval_s's twin for the overlapped ring path:
        one seed dispatch pins the sharded tensors + per-shard
        precompute on the mesh, then `reps` ring sweeps run back to
        back with the rotating peer bundle DONATED and fed forward
        (engine/tiled.py ring_counts_pipeline), one readback at the
        end.  Returns (seconds_per_eval, counts), or None for an empty
        problem."""
        self._check_ips()
        n = self.encoding.cluster.n_pods
        if not cases or n == 0:
            return None
        planspec.record("counts.ring.pipelined")
        from .tiled import evaluate_grid_counts_ring_pipelined

        return evaluate_grid_counts_ring_pipelined(
            self._tensors_with_cases(cases), n, reps=reps, block=block,
            mesh=mesh,
        )

    def evaluate_grid_counts_ring2d(
        self, cases: Sequence[PortCase], block: int = 1024, mesh=None
    ) -> Dict[str, int]:
        """Hierarchical multi-host ring counts over a ("dcn", "ici") mesh:
        ring hops ride the intra-host ICI ring and cross the DCN host
        boundary once per round (engine/tiled.py ring2d).  The multi-host
        scale-out path."""
        self._check_ips()
        n = self.encoding.cluster.n_pods
        if not cases or n == 0:
            return {"ingress": 0, "egress": 0, "combined": 0, "cells": 0}
        planspec.record("counts.ring2d")
        from .tiled import evaluate_grid_counts_ring2d

        return evaluate_grid_counts_ring2d(
            self._tensors_with_cases(cases), n, block=block, mesh=mesh
        )

    def iter_grid_blocks(self, cases: Sequence[PortCase], block: int = 1024):
        """Stream verdict blocks of source rows to the host:
        yields (start, ingress_rows, egress, combined), arrays [b, N, Q]
        bool.  For consumers that scan grids bigger than host/device
        memory."""
        from .tiled import iter_grid_blocks

        self._check_ips()
        n = self.encoding.cluster.n_pods
        if not cases or n == 0:
            return iter(())
        planspec.record("grid.blocks")
        return iter_grid_blocks(self._tensors_with_cases(cases), n, block=block)

    def evaluate_pairs(
        self, cases: Sequence[PortCase], pairs: Sequence[Tuple[int, int]]
    ) -> np.ndarray:
        """Point verdicts for (src_idx, dst_idx) pod pairs: [K, Q, 3] bool
        (ingress, egress, combined) — no N x N grid anywhere, so it scales
        to arbitrary cluster sizes (powers the large-scale parity spot
        checks in bench.py)."""
        from .tiled import evaluate_pairs_kernel

        self._check_ips()
        if not cases or len(pairs) == 0:
            return np.zeros((len(pairs), len(cases), 3), dtype=bool)
        planspec.record("pairs.aot")
        idx = np.asarray(pairs, dtype=np.int32).reshape(-1, 2)
        if self._pairs_aot is None:
            # the serve query path's program: a restarted serve replica
            # adopts it from the AOT cache before its first verdict
            self._pairs_aot = aot_cache.AotProgram(
                "pairs", evaluate_pairs_kernel, plan=self._aot_plan()
            )
        with ti.eval_flight(
            "pairs", self.encoding.cluster.n_pods, len(cases), k=len(pairs)
        ):
            out = self._pairs_aot(
                self._tensors_with_cases(cases, device=True), idx[:, 0], idx[:, 1]
            )
        return np.stack(
            [
                np.asarray(out["ingress"]),
                np.asarray(out["egress"]),
                np.asarray(out["combined"]),
            ],
            axis=2,
        )

    def firing_components(
        self, cases: Sequence[PortCase]
    ) -> Dict[str, Dict[str, np.ndarray]]:
        """Per-direction RULE firing-mask components on the RAW encoding
        (no dead-target compaction, no shape bucketing), so flat peer row
        p maps 1:1 to resolved rule (peer_target[p], peer_rule_idx[p]) of
        the policy's sorted_targets() order — the contract the analysis
        subsystem (cyclonus_tpu.analysis) audits on.

        Returns {direction: {rule_tmatch [P, N], peer_match [P, N],
        pport [P, Q], has_target [N]}} numpy bool arrays; rule p's firing
        mask over (target-side pod n, peer-side pod m, case q) is
        rule_tmatch[p, n] & peer_match[p, m] & pport[p, q]."""
        from .kernel import rule_firing_kernel

        self._check_ips()
        planspec.record("firing.raw")
        raw = self._build_tensors()
        q_port, q_name, q_proto = self._port_case_arrays(cases)
        # "tiers" excluded on purpose: firing masks are a NetworkPolicy-
        # TIER concept (rule = one peer matcher of one target).  The
        # audit built on them stays sound under the lattice — see
        # analysis/audit.py's tier-composition note — because removing a
        # shadowed NP rule changes neither has_target nor any any_allow
        # cell, and the lattice reads the NP tier only through those two.
        shared = {
            k: v
            for k, v in raw.items()
            if k not in ("ingress", "egress", "tiers")
        }
        shared["q_port"] = q_port
        shared["q_name"] = q_name
        shared["q_proto"] = q_proto
        out = {}
        for direction in ("ingress", "egress"):
            comp = rule_firing_kernel(shared, raw[direction])
            out[direction] = {k: np.asarray(v) for k, v in comp.items()}
        return out

    def evaluate_grid_sharded(
        self, cases: Sequence[PortCase], mesh=None, schedule=None
    ) -> GridVerdict:
        """Mesh-sharded evaluation: the shard_map program runs over `mesh`
        (default: all devices of the default backend, or the virtual CPU
        mesh when the default backend is a single chip — see
        sharded.default_mesh).  `schedule` picks the peer exchange:
        "ring" (overlapped ppermute streaming, the default) or
        "allgather" (the replicated reference) — bit-identical grids
        either way.  A 1-device mesh still runs the sharded program;
        use evaluate_grid for the plain single-device kernel."""
        from .sharded import evaluate_grid_sharded, mesh_schedule

        self._check_ips()
        if not cases:
            return self.evaluate_grid(cases)
        if self._class_state is not None:
            return self._evaluate_grid_sharded_classes(
                cases, mesh, schedule=schedule
            )
        # record at the dispatch leaf, not inside the shared shard_map
        # primitive (the compressed route reuses it over the class axis)
        if mesh_schedule(schedule) == "ring":
            planspec.record("grid.sharded.ring")
        else:
            planspec.record("grid.sharded.allgather")
        tensors = self._tensors_with_cases(cases)
        import jax.numpy as jnp

        with phase("engine.dispatch_sharded"):
            ingress, egress, combined = evaluate_grid_sharded(
                tensors, self.encoding.cluster.n_pods, mesh=mesh,
                schedule=schedule,
            )
        return GridVerdict(
            self.pod_keys,
            list(cases),
            jnp.moveaxis(ingress, -1, 0),
            jnp.moveaxis(egress, -1, 0),
            jnp.moveaxis(combined, -1, 0),
        )


def _parseable_ip(ip: str) -> bool:
    try:
        ipaddress.ip_address(ip)
        return True
    except ValueError:
        return False
