"""Tuple-space / longest-prefix-match pre-classification of CIDR-heavy
policy sets (docs/DESIGN.md "CIDR tuple-space pre-classification").

The class-compression wall this breaks: the per-pod observability
signature (encoding.pod_signatures) spends one bit per DISTINCT
(base, mask, excepts) ip-peer spec.  An ipBlock-heavy set — 100k
distinct CIDRs, the internet-facing egress case — makes that signature
O(specs) bits per pod: a [specs, N] bool membership pass that is 10 GB
of host temporaries at the 100k x 100k shape, so compression silently
degrades to the dense N x N x Q grid exactly where it is needed most.

The tuple-space observation (TaNG / "A Computational Approach to Packet
Classification", PAPERS.md): group CIDR atoms by MASK.  Within one mask
partition, `pod_ip & mask` is a single value, so a pod can match AT
MOST ONE base — the whole partition's membership pattern collapses to
one integer: the index of the matched atom, or -1.  The per-pod
signature for the entire CIDR dimension is therefore a [K] int32 vector
(K = distinct masks, <= 33 for IPv4) instead of [specs] bits, and the
lookup is a binary search over each partition's sorted bases — the
flattened form of a prefix-trie walk (sorted prefixes ARE the trie's
leaf order; bisecting them descends it).

Soundness: every spec's membership bit is a boolean function of its
primary atom's hit and its except atoms' hits, all of which the
partition signature determines — so pods with equal signatures have
equal membership on every spec, equal verdict rows, and may share a
class (encoding.py class-compression design note; the bridge is proven
mechanically by spec_membership_words + the fuzz CIDR family).  The
signature may be FINER than the per-spec bits (two pods hitting
different except-only atoms split), which costs classes, never
correctness.

Family routing: only in-kernel IPv4 rows (`ip_is_v4`) contribute atoms.
Host-evaluated rows — IPv6 CIDRs and v4 blocks with mixed-family
excepts (encoding._encode_direction) — keep their per-pod match COLUMNS
in the signature exactly as before: the trie never sees a v6 row.

Gating (`CYCLONUS_CIDR_TSS`): "auto" (default) engages above
CYCLONUS_CIDR_TSS_MIN distinct specs — below it the per-spec bit path
is smaller and faster; "1" forces (tests, `make parity-cidr`); "0"
disables, restoring the pre-TSS signature bytes exactly.  The stage
falls back to the dense bit path (returns None) when the partition
tensors plus the staged [K, N] signature would not fit
CYCLONUS_SLAB_MAX_BYTES — the same budget every other device tensor
charges (api._class_aux_bytes counts the partition tensors too).

The device leg (kernel.lpm_partition_signature, wrapped in an
AotProgram so a restarted process adopts the compiled binary) runs the
same searchsorted walk on accelerator for large pod x atom products;
the numpy twin here is the small-case path and the differential check —
the two are pinned bit-identical by tests/test_engine_cidr.py.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

import logging

from ..utils import contracts
from .encoding import iter_ip_specs, pack_bool_words
from .pallas_kernel import lane_round_up

logger = logging.getLogger(__name__)

#: pad value for partition base buckets: sorts after every real base of
#: its row (reals are placed first, so a real 255.255.255.255/32 still
#: wins the leftmost-searchsorted tie); the paired pindex pad is -1,
#: which is what actually rejects a pad hit
_BASE_PAD = np.uint32(0xFFFFFFFF)


def tss_mode(mode: Optional[str] = None) -> str:
    """Resolve CYCLONUS_CIDR_TSS: "auto" (default — engage above the
    distinct-spec floor), "1" (force), "0" (off: signature bytes exactly
    the pre-TSS per-spec bit path).  Resolved EAGERLY at build time and
    never read inside a traced function (the encoding.pack_enabled
    discipline)."""
    import os

    if mode is None:
        mode = os.environ.get("CYCLONUS_CIDR_TSS", "auto")
    mode = str(mode).lower()
    if mode not in ("auto", "0", "1"):
        raise ValueError(
            f"CYCLONUS_CIDR_TSS must be auto, 0, or 1, got {mode!r}"
        )
    return mode


def tss_min_specs() -> int:  # never-raises
    """Auto-mode floor on distinct (base, mask, excepts) specs: below
    it, one bit per spec is cheaper than 4 bytes per partition and the
    dense membership pass is noise (CYCLONUS_CIDR_TSS_MIN overrides)."""
    import os

    try:
        return int(os.environ.get("CYCLONUS_CIDR_TSS_MIN", "256"))
    except Exception as e:  # malformed env degrades to the default
        logger.debug("malformed CYCLONUS_CIDR_TSS_MIN: %s", e)
        return 256


def device_min_cells() -> int:  # never-raises
    """pods x atoms floor above which the LPM stage runs on device
    (CYCLONUS_CIDR_TSS_DEVICE=1/0 forces/forbids): below it the numpy
    twin beats a device round trip."""
    import os

    try:
        return int(os.environ.get("CYCLONUS_CIDR_DEVICE_MIN", str(1 << 24)))
    except Exception as e:  # malformed env degrades to the default
        logger.debug("malformed CYCLONUS_CIDR_DEVICE_MIN: %s", e)
        return 1 << 24


@contracts.checked
@dataclass
class CidrSpace:
    """The TSS partition map of one engine's ip-peer rows.

    Tensor contracts: A atoms (distinct (base, mask) over primary CIDRs
    and their excepts, both directions), K partitions (distinct masks,
    LPM order: longest prefix first), B the lane-padded bucket width
    (pallas_kernel.lane_round_up).  `pbases` rows hold each partition's
    bases sorted ascending with _BASE_PAD fill; `pindex` holds the
    matching GLOBAL atom index with -1 fill — the -1, not the pad base
    value, is what rejects a pad hit, so a real 0xFFFFFFFF base is safe.
    Validated on construction under CYCLONUS_SHAPE_CHECK=1."""

    n_specs: int  # distinct (base, mask, excepts) rows (the bit path's width)
    n_atoms: int
    n_host_rows: int  # host-evaluated (v6/mixed) rows routed AROUND the trie
    atom_base: np.ndarray = contracts.tensor("(A,) uint32")
    atom_mask: np.ndarray = contracts.tensor("(A,) uint32")
    atom_part: np.ndarray = contracts.tensor("(A,) int32")  # atom -> partition
    pmask: np.ndarray = contracts.tensor("(K,) uint32")
    pprefix: np.ndarray = contracts.tensor("(K,) int32")
    pbases: np.ndarray = contracts.tensor("(K, B) uint32")
    pindex: np.ndarray = contracts.tensor("(K, B) int32", sentinel="-1=pad")
    #: per spec: (primary atom id, tuple of except atom ids) — the
    #: bridge from partition signatures back to per-spec membership
    #: (spec_membership_words); python-side, row order = spec discovery
    spec_atoms: List[Tuple[int, Tuple[int, ...]]] = field(default_factory=list)
    #: forensics of the last signature computation (bench detail.cidr)
    last_lpm_s: Optional[float] = None
    last_device: Optional[bool] = None

    @property
    def n_partitions(self) -> int:
        return int(self.pmask.shape[0])

    @property
    def max_bucket(self) -> int:
        return int(self.pbases.shape[1])

    def nbytes(self) -> int:
        """Device bytes of the partition tensors — charged against
        CYCLONUS_SLAB_MAX_BYTES via api._class_aux_bytes."""
        return int(
            self.atom_base.nbytes
            + self.atom_mask.nbytes
            + self.atom_part.nbytes
            + self.pmask.nbytes
            + self.pprefix.nbytes
            + self.pbases.nbytes
            + self.pindex.nbytes
        )

    def structure(self) -> Tuple:
        """The partition-map identity serve's incremental patch path
        compares (serve/incremental.py patch_policy): a policy delta
        whose mask structure differs must go Ineligible -> full rebuild
        rather than patch over a stale map."""
        return tuple(int(m) for m in self.pmask)

    def signature(
        self,
        pod_ip: np.ndarray,
        pod_ip_valid: np.ndarray,
        device: Optional[bool] = None,
    ) -> np.ndarray:
        """[K, N] int32 per-pod partition signature: the GLOBAL index of
        the one atom of partition k that pod n's IP matches, or -1
        (no match / invalid IP).  device=None auto-routes by work size;
        the two legs are bit-identical (tests/test_engine_cidr.py)."""
        import time

        n = int(pod_ip.shape[0])
        if device is None:
            device = _device_enabled(n * max(self.n_atoms, 1))
        t0 = time.perf_counter()
        if device and n:
            import jax

            out = np.asarray(
                _lpm_program()(
                    jax.device_put(np.ascontiguousarray(pod_ip)),
                    jax.device_put(np.ascontiguousarray(pod_ip_valid)),
                    jax.device_put(self.pmask),
                    jax.device_put(self.pbases),
                    jax.device_put(self.pindex),
                )
            )
        else:
            out = self.signature_host(pod_ip, pod_ip_valid)
            device = False
        self.last_lpm_s = time.perf_counter() - t0
        self.last_device = bool(device)
        return out

    def signature_host(
        self, pod_ip: np.ndarray, pod_ip_valid: np.ndarray
    ) -> np.ndarray:
        """Numpy twin of kernel.lpm_partition_signature, op for op:
        mask, leftmost binary search per partition, gather, reject pads
        via pindex -1 and invalid IPs via the validity mask."""
        k = self.n_partitions
        n = int(pod_ip.shape[0])
        key = pod_ip[None, :] & self.pmask[:, None]  # [K, N] uint32
        pos = np.empty((k, n), dtype=np.int64)
        for ki in range(k):
            pos[ki] = np.searchsorted(self.pbases[ki], key[ki], side="left")
        pos = np.minimum(pos, self.max_bucket - 1)
        hit = np.take_along_axis(self.pbases, pos, axis=1) == key
        idx = np.take_along_axis(self.pindex, pos, axis=1)
        return np.where(
            hit & (idx >= 0) & pod_ip_valid[None, :], idx, np.int32(-1)
        ).astype(np.int32)


def _collect(tensors: Dict):
    """(specs, atoms, n_host_rows) over both directions' in-kernel IPv4
    ip-peer rows: specs come from encoding.iter_ip_specs — the ONE spec
    identity the dense bit path also buckets on, so the two paths can
    never disagree on what "distinct CIDR" means; atoms dedup on
    (base, mask) over primaries and excepts.  Host-evaluated rows
    (host_ip_mask) are counted but contribute NO atoms — they stay on
    the host column path."""
    specs = iter_ip_specs(tensors)
    atoms: Dict[Tuple[int, int], int] = {}
    for base, mask, exs in specs:
        atoms.setdefault((base, mask), 0)
        for eb, em in exs:
            atoms.setdefault((eb, em), 0)
    n_host = 0
    for direction in ("ingress", "egress"):
        d = tensors[direction]
        if "host_ip_mask" in d:
            n_host += int(np.count_nonzero(d["host_ip_mask"]))
    return specs, atoms, n_host


def build_space(tensors: Dict) -> Optional[CidrSpace]:
    """The CidrSpace of `tensors`' ip-peer rows, or None when no
    in-kernel IPv4 row exists.  Deterministic in the tensor contents
    alone (masks sorted longest-prefix-first, bases ascending, global
    atom ids in (partition, base) order), so build-time and serve-time
    derivations of the same tensors always agree."""
    specs, atoms, n_host = _collect(tensors)
    if not atoms:
        return None
    # partitions: distinct masks, longest prefix first (mask values are
    # monotone in prefix length, so numeric-descending IS the LPM order)
    masks = sorted({m for _b, m in atoms}, reverse=True)
    part_of = {m: k for k, m in enumerate(masks)}
    buckets: List[List[int]] = [[] for _ in masks]
    for b, m in atoms:
        buckets[part_of[m]].append(b)
    for bl in buckets:
        bl.sort()
    # global atom ids in (partition, base) order — the signature values
    atom_id: Dict[Tuple[int, int], int] = {}
    a_base: List[int] = []
    a_mask: List[int] = []
    a_part: List[int] = []
    for k, m in enumerate(masks):
        for b in buckets[k]:
            atom_id[(b, m)] = len(a_base)
            a_base.append(b)
            a_mask.append(m)
            a_part.append(k)
    b_max = max(len(bl) for bl in buckets)
    b_pad = lane_round_up(b_max)  # tile: 128
    pbases = np.full((len(masks), b_pad), _BASE_PAD, dtype=np.uint32)
    pindex = np.full((len(masks), b_pad), -1, dtype=np.int32)
    for k, m in enumerate(masks):
        for j, b in enumerate(buckets[k]):
            pbases[k, j] = b
            pindex[k, j] = atom_id[(b, m)]
    spec_atoms = [
        (atom_id[(base, mask)], tuple(atom_id[(eb, em)] for eb, em in exs))
        for (base, mask, exs) in specs
    ]
    return CidrSpace(
        n_specs=len(specs),
        n_atoms=len(a_base),
        n_host_rows=n_host,
        atom_base=np.array(a_base, dtype=np.uint32).reshape(-1),
        atom_mask=np.array(a_mask, dtype=np.uint32).reshape(-1),
        atom_part=np.array(a_part, dtype=np.int32).reshape(-1),
        pmask=np.array(masks, dtype=np.uint32).reshape(-1),
        pprefix=np.array(
            [bin(m).count("1") for m in masks], dtype=np.int32
        ).reshape(-1),
        pbases=pbases,
        pindex=pindex,
        spec_atoms=spec_atoms,
    )


def resolve(
    tensors: Dict,
    mode: Optional[str] = None,
    n_pods: Optional[int] = None,
) -> Optional[CidrSpace]:
    """The gated entry point: the CidrSpace the class machinery should
    use, or None for the dense per-spec bit path — off (mode "0"), no
    IPv4 atoms, unprofitable (auto below the distinct-spec floor), or
    over the HBM budget (partition tensors + the staged [K, N]
    signature vs CYCLONUS_SLAB_MAX_BYTES)."""
    m = tss_mode(mode)
    if m == "0":
        return None
    space = build_space(tensors)
    if space is None:
        return None
    if m == "auto" and space.n_specs < tss_min_specs():
        return None
    if n_pods is None:
        n_pods = int(tensors["pod_ip"].shape[0]) if "pod_ip" in tensors else 0
    from ..utils import envflags

    budget = envflags.get_int("CYCLONUS_SLAB_MAX_BYTES")
    staged = space.nbytes() + 4 * space.n_partitions * n_pods + 4 * n_pods
    if staged > budget:
        return None
    return space


def mask_structure(space: Optional[CidrSpace]) -> Optional[Tuple]:
    """The comparable partition-map identity (None = stage inactive) —
    what serve's patch_policy pins across a policy delta."""
    return None if space is None else space.structure()


def _device_enabled(cells: int) -> bool:
    """Route the LPM stage to the accelerator?  CYCLONUS_CIDR_TSS_DEVICE
    "1"/"0" force/forbid; default: above the pods x atoms work floor."""
    import os

    forced = os.environ.get("CYCLONUS_CIDR_TSS_DEVICE", "auto").lower()
    if forced == "1":
        return True
    if forced == "0":
        return False
    return cells >= device_min_cells()


_LPM_PROGRAM = None  # cache-key: shapes (AotProgram: name/signature/platform/plan)


def _lpm_program():
    """The AotProgram-wrapped LPM kernel (kernel.lpm_partition_signature):
    pure function of its array arguments — nothing value-baked — so the
    persisted key is (name, shape signature, platform, plan) and a
    restarted process adopts the executable with zero traces."""
    global _LPM_PROGRAM
    if _LPM_PROGRAM is None:
        import jax

        from . import aot_cache
        from .kernel import lpm_partition_signature

        _LPM_PROGRAM = aot_cache.AotProgram(
            "cidr.lpm", jax.jit(lpm_partition_signature), plan="lpm32-v1"
        )
    return _LPM_PROGRAM


def dense_spec_membership(
    space: CidrSpace, pod_ip: np.ndarray, pod_ip_valid: np.ndarray
) -> np.ndarray:
    """[n_specs, N] bool per-spec membership by the DENSE mask-compare —
    the reference semantics (kernel.direction_precompute's
    in_cidr & ~in_except, validity-masked) the soundness bridge checks
    spec_membership_words against.  One implementation on purpose: the
    fuzz CIDR gate and the twin tests all compare against THIS."""
    am = pod_ip_valid[None, :] & (
        (pod_ip[None, :] & space.atom_mask[:, None])
        == space.atom_base[:, None]
    )  # [A, N] atom membership
    n = int(pod_ip.shape[0])
    bits = np.zeros((max(space.n_specs, 1), n), dtype=bool)
    for s, (primary, excepts) in enumerate(space.spec_atoms):
        m = am[primary].copy()
        for ea in excepts:
            m &= ~am[ea]
        bits[s] = m
    return bits


def spec_membership_words(space: CidrSpace, sig: np.ndarray) -> np.ndarray:
    """[W, N] int32 packed per-SPEC membership words recovered from a
    [K, N] partition signature (W = encoding.packed_words(n_specs), the
    PR 11 32-per-word layout via pack_bool_words): spec s matches pod n
    iff its primary atom is n's match in that atom's partition and no
    except atom is.  This is the mechanical bridge from the TSS
    signature back to the dense bit semantics — the parity tests pin it
    equal to the membership bits kernel.direction_precompute computes
    (in_cidr & ~in_except), which is the soundness argument for feeding
    partition signatures to compute_pod_classes."""
    n = int(sig.shape[1])
    bits = np.zeros((max(space.n_specs, 1), n), dtype=bool)
    for s, (primary, excepts) in enumerate(space.spec_atoms):
        m = sig[int(space.atom_part[primary])] == primary
        for ea in excepts:
            m &= ~(sig[int(space.atom_part[ea])] == ea)
        bits[s] = m
    return pack_bool_words(bits, axis=0)
