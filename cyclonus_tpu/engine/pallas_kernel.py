"""Pallas TPU kernel: fused verdict-tile + count reduction.

The XLA tiled counts path (tiled.py) materializes per-tile boolean verdict
blocks and f32 matmul outputs in HBM before reducing them.  This kernel
fuses the whole per-tile epilogue —

    egress   = (tmatch_e_blk'^T @ tallow_e') > 0
    ingress  = (tallow_i_blk'^T @ tmatch_i') > 0
    combined = egress AND ingress
    counts  += [sum ingress, sum egress, sum combined]

— into VMEM: a blocked matmul over grid (q, src-tile, dst-tile, T-chunk)
with two f32 accumulators in scratch and a count epilogue on the last
T-chunk.  The three N x N x Q verdict tensors never exist anywhere.
The primed operands carry one extra PSEUDO-TARGET row per direction that
encodes both the allow-if-no-matching-target rule and the pod-validity
mask (verdict_counts_pallas docstring), so the epilogue needs no
correction terms.

Decision procedure mirrors tiled._tile_verdicts / kernel.py (reference
policy.go:138-174); parity vs the XLA paths is enforced by
tests/test_engine_pallas.py (interpret mode on CPU, compiled on TPU).

Layout notes:
  * all matmul operands are pre-cast to bf16; accumulation is f32 on the
    MXU, so the > 0 threshold is exact (0/1 inputs).
  * the pod axis is padded to the lane-aligned tile BD and the target
    axis to the chunk KT with zeros: padded targets match nothing and
    allow nothing; padded pods fail the pseudo-target's validity gate,
    so their rows and columns count as zero with no explicit mask.
  * counts accumulate into a per-(port case, src-tile) int32 output block
    (the standard reduction-output pattern); lanes 0-2 hold ingress/
    egress/combined.  Per-block partials are bounded by bs * N with bs
    chosen by _tiles_for (512 or 1024), which checks exactly this bound
    before doubling; the host sums them in int64 (a single global int32
    accumulator overflowed at 100k pods).
"""

from __future__ import annotations

import math
import os
from functools import partial
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..telemetry import instruments as ti

# base tile sizes: BS/BD are the src/dst tile heights (MXU-aligned), KT
# the MAX target-axis chunk.  The actual per-call sizes come from
# _kt_for (shrinks KT to the live target count) and _tiles_for (doubles
# the src tile to 1024 when the smaller chunks leave VMEM room) — the
# VMEM/overflow budgets live in those two functions.
BS = 512
BD = 512
KT = 1024


def _kt_for(n_targets: int) -> int:
    """Per-direction target-axis chunk: lane-aligned (128) and no larger
    than needed.  Target counts after dead-target compaction are often
    far below the max chunk (e.g. ~300 at the 10k-policy bench config);
    padding them to a fixed 1024 would multiply both the contraction
    depth (matmul flops) and the [Q, KT, N] operand's HBM footprint —
    the single-chip memory ceiling at multi-million-pod scale."""
    return max(128, min(KT, lane_round_up(n_targets)))


def lane_round_up(n: int) -> int:
    """Smallest multiple of the 128-lane tile >= n (>= 128) — THE
    ceil-div round-up shapelint SC004 discharges for the target chunks
    (_kt_for above), factored out so lane alignment has one formula,
    not several hand-rolled copies."""
    return -(-max(int(n), 1) // 128) * 128


def _tiles_for(
    kt_e: int,
    kt_i: int,
    n: int,
    single_chunk_int8: bool = False,
    n_dst: int = None,
) -> Tuple[int, int]:
    """Src/dst tile heights.  From the default (512, 512), double the src
    tile when (a) the T-chunks leave VMEM room for the bigger blocks +
    scratch and (b) per-(q, src-tile) int32 count partials stay below
    2^31 — fewer grid steps amortize the per-step epilogue/DMA overhead
    (bench-measured 56 -> 68 e9 cells/s at the 100k x 10k config).  On
    the scratch-free single-chunk int8 path the blocks are half the
    bytes and there are no accumulator tiles, so (2048, 1024) fits and
    measures fastest (0.27 -> 0.19 s at the bench config).  The count
    bound is per (src tile x FULL dst axis), so rectangular callers pass
    n_dst (defaults to n for the square case).  A non-default BS/BD
    (tests sweep them) is honored as-is."""
    if n_dst is None:
        n_dst = n
    bs, bd = BS, BD
    if (bs, bd) != (512, 512):
        return bs, bd
    if single_chunk_int8:
        # VMEM gate on the actual chunk sizes, not just the int32 count
        # bound: the (2048, 1024) tile's double-buffered int8 input
        # blocks are 2 * (kt_e + kt_i) * (2048 + 1024) bytes, and the
        # two [2048, 1024] int32 matmul intermediates add ~16 MiB more
        # against the ~16 MiB/core VMEM budget.  The bench regime
        # (kt_e + kt_i ~ 640 after compaction) fits with room; with both
        # directions near the 1024 chunk max (~12 MiB of blocks alone)
        # Mosaic compilation would fail at runtime — cap the blocks at
        # 6 MiB (kt_e + kt_i <= 1024) and fall through to the 512-tile
        # path, whose own budget accounts for kt, when it doesn't fit.
        blocks_1chunk = 2 * (kt_e + kt_i) * (2048 + 1024)  # int8, dbuf
        if (
            n > 2 * bs
            and 2048 * (n_dst + 4096) < 2**31
            and blocks_1chunk <= 6 * 2**20
        ):
            return 2048, 1024
        # fall through to the doubled-bs check for mid-size clusters
    blocks = 4 * (kt_e + kt_i) * (2 * bs + bd)  # bf16, double-buffered
    scratch = 2 * 4 * (2 * bs) * bd  # two f32 accumulators
    if (
        n > bs  # a single default tile already holds the whole problem
        and blocks + scratch <= 12 * 2**20
        and 2 * bs * (n_dst + 2048) < 2**31
    ):
        bs *= 2
    return bs, bd


def _make_verdict_counts_kernel(n_k_e: int, n_k_i: int):
    """Kernel body specialized on the per-direction T-chunk counts: the
    two directions usually pad to different target-axis lengths (egress
    targets are a subset of policies), and multiplying the shorter
    direction's zero chunks would waste up to ~⅓ of the MXU work.

    Content skip: the nz_e/nz_i scalar-prefetch maps mark which
    (pod-tile, T-chunk) tmatch blocks contain any nonzero.  With pods
    and targets namespace-sorted (api._counts_pallas_packed) tmatch is
    near block diagonal, so most blocks are empty and their matmuls are
    skipped entirely — this is where the 10k-policy regime's T-axis
    flops go."""
    ti.KERNEL_TRACES.inc(kernel="counts_chunked")

    def _verdict_counts_kernel(
        nz_e_ref,  # [n_i * n_k_e] int32 scalar-prefetch: tmatch_e block nonzero
        nz_i_ref,  # [n_k_i * n_j] int32 scalar-prefetch: tmatch_i block nonzero
        redir_e_ref,  # [n_i * n_k_e] int32: last nonzero chunk <= k (DMA reuse)
        redir_i_ref,  # [n_k_i * n_j] int32: last nonzero chunk <= k (DMA reuse)
        a_e_ref,  # [BS, KT] bf16   tmatch_e^T src block, T-chunk k
        b_e_ref,  # [1, KT, BD] bf16  tallow_e (q, T-chunk k, dst block j)
        b_i_ref,  # [1, KT, BS] bf16  tallow_i (q, T-chunk k, src block i)
        a_i_ref,  # [KT, BD] bf16   tmatch_i (T-chunk k, dst block j)
        counts_ref,  # [1, n_i, 128] int32: per-q count plane, row per src-tile
        acc_e_ref,  # [BS, BD] f32 scratch
        acc_i_ref,  # [BS, BD] f32 scratch
        cnt_ref,  # [1, 128] int32 scratch: running counts for this (q, i)
    ):
        i = pl.program_id(1)
        j = pl.program_id(2)
        k = pl.program_id(3)
        n_j = pl.num_programs(2)
        n_k = pl.num_programs(3)

        # counts accumulate into a per-(q, src-tile) ROW of the per-q count
        # plane: a single global accumulator overflows int32 once allowed
        # cells exceed 2^31 (seen at 100k pods); per-row partials are bounded
        # by the _tiles_for-checked bs * N < 2^31.  (The plane is the output block — a (1, 1, 128)
        # block would violate the Mosaic (8, 128) tiling rule for n_i > 1.)
        @pl.when((i == 0) & (j == 0) & (k == 0))
        def _init_counts():
            counts_ref[:] = jnp.zeros_like(counts_ref)

        @pl.when(k == 0)
        def _init_acc():
            acc_e_ref[:] = jnp.zeros_like(acc_e_ref)
            acc_i_ref[:] = jnp.zeros_like(acc_i_ref)

        @pl.when((j == 0) & (k == 0))
        def _init_cnt():
            cnt_ref[:] = jnp.zeros_like(cnt_ref)

        # egress[b, d] += sum_t tmatch_e[t, src b] * tallow_e[t, dst d].
        # Guarded per direction: for k >= n_k_dir the clamped index maps
        # REFETCH the direction's last real chunk (not zeros), so the
        # accumulate must be skipped, not relied on to be a no-op; and an
        # all-zero tmatch block contributes nothing, so its matmul is
        # skipped by content (nz map).
        acc_dt = acc_e_ref.dtype  # int32 for int8 operands, f32 for bf16

        @pl.when((k < n_k_e) & (nz_e_ref[i * n_k_e + jnp.minimum(k, n_k_e - 1)] > 0))
        def _acc_egress():
            acc_e_ref[:] += jnp.dot(
                a_e_ref[:], b_e_ref[0], preferred_element_type=acc_dt
            )

        # ingress[b, d] += sum_t tallow_i[t, src b] * tmatch_i[t, dst d]
        @pl.when((k < n_k_i) & (nz_i_ref[jnp.minimum(k, n_k_i - 1) * n_j + j] > 0))
        def _acc_ingress():
            acc_i_ref[:] += jax.lax.dot_general(
                b_i_ref[0],
                a_i_ref[:],
                dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=acc_dt,
            )

        @pl.when(k == n_k - 1)
        def _epilogue():
            # The no-matching-target => allow rule and the pod validity
            # mask are FOLDED INTO THE MATMUL as one pseudo-target row per
            # direction (see verdict_counts_pallas): acc > 0 IS the final
            # verdict, and invalid (padded) pods produce all-False rows/
            # columns, so the counts need no masking.  This epilogue runs
            # for every (src, dst) tile pair — at multi-million-pod scale
            # its per-cell VPU work, not the MXU matmuls, is the kernel
            # floor, so every fused op here was measured to matter.  (A
            # variant that rode the count reductions on the MXU as thin
            # ones-vector f32 contractions measured ~10% SLOWER at the
            # 100k bench — thin f32 matmuls underutilize the systolic
            # array more than the VPU tree-reduce costs.)
            zero = jnp.array(0, acc_dt)
            egress = acc_e_ref[:] > zero
            ingress = acc_i_ref[:] > zero
            combined = egress & ingress
            c_in = jnp.sum(ingress.astype(jnp.int32))
            c_eg = jnp.sum(egress.astype(jnp.int32))
            c_co = jnp.sum(combined.astype(jnp.int32))
            lane = jax.lax.broadcasted_iota(jnp.int32, (1, 128), 1)
            cnt_ref[:] += (
                jnp.where(lane == 0, c_in, 0)
                + jnp.where(lane == 1, c_eg, 0)
                + jnp.where(lane == 2, c_co, 0)
            )
            # flush to this (q, i)'s row of the count plane once per src-tile
            # (the dynamic-row store is the expensive part)
            @pl.when(j == n_j - 1)
            def _flush():
                counts_ref[:, pl.ds(i, 1), :] = cnt_ref[:].reshape(1, 1, 128)

    return _verdict_counts_kernel


def _make_verdict_counts_kernel_1chunk():
    """Kernel body for the SINGLE T-chunk case (n_k_e == n_k_i == 1),
    which is the common regime after dead-target compaction: both
    directions' live targets fit one lane-aligned chunk (<= 1024), so
    there is nothing to accumulate across k.  The general kernel pays,
    per grid step: two scratch zero-inits, two matmul accumulations into
    VMEM scratch, and an epilogue that re-reads both scratch tiles —
    ~8 MB of VMEM round-trips per step that this body skips entirely by
    keeping the matmul results in registers straight into the count
    epilogue.  The nz/redir skip machinery is also dropped: the
    pseudo-target row lives in the (only) chunk, so no block is ever
    all-zero."""
    ti.KERNEL_TRACES.inc(kernel="counts_1chunk")

    def _verdict_counts_kernel_1chunk(
        a_e_ref,  # [BS, KT] bf16   tmatch_e^T src block
        b_e_ref,  # [1, KT, BD] bf16  tallow_e (q, dst block j)
        b_i_ref,  # [1, KT, BS] bf16  tallow_i (q, src block i)
        a_i_ref,  # [KT, BD] bf16   tmatch_i (dst block j)
        counts_ref,  # [1, n_i, 128] int32 per-q count plane
        cnt_ref,  # [1, 128] int32 scratch: running counts for this (q, i)
    ):
        i = pl.program_id(1)
        j = pl.program_id(2)
        n_j = pl.num_programs(2)

        @pl.when((i == 0) & (j == 0))
        def _init_counts():
            counts_ref[:] = jnp.zeros_like(counts_ref)

        @pl.when(j == 0)
        def _init_cnt():
            cnt_ref[:] = jnp.zeros_like(cnt_ref)

        acc_dt = jnp.int32 if a_e_ref.dtype == jnp.int8 else jnp.float32
        acc_e = jnp.dot(
            a_e_ref[:], b_e_ref[0], preferred_element_type=acc_dt
        )
        acc_i = jax.lax.dot_general(
            b_i_ref[0],
            a_i_ref[:],
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=acc_dt,
        )
        zero = jnp.array(0, acc_dt)
        egress = acc_e > zero
        ingress = acc_i > zero
        combined = egress & ingress
        c_in = jnp.sum(ingress.astype(jnp.int32))
        c_eg = jnp.sum(egress.astype(jnp.int32))
        c_co = jnp.sum(combined.astype(jnp.int32))
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, 128), 1)
        cnt_ref[:] += (
            jnp.where(lane == 0, c_in, 0)
            + jnp.where(lane == 1, c_eg, 0)
            + jnp.where(lane == 2, c_co, 0)
        )

        @pl.when(j == n_j - 1)
        def _flush():
            counts_ref[:, pl.ds(i, 1), :] = cnt_ref[:].reshape(1, 1, 128)

    return _verdict_counts_kernel_1chunk


def _pad_to(x: jnp.ndarray, axis: int, mult: int) -> jnp.ndarray:
    """Zero-pad `axis` up to a multiple of `mult` — at least one full
    chunk, so a zero-size axis (e.g. a direction with no targets) still
    yields a valid block (all-zero = matches nothing, allows nothing)."""
    n = x.shape[axis]
    pad = mult if n == 0 else (-n) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _resolve_operand_dtype(operand_dtype: str | None) -> str:
    """CYCLONUS_PALLAS_DTYPE, resolved OUTSIDE the jitted kernels and
    passed in as a static argument: the module-level jit caches are
    keyed on shapes plus statics, so for DIRECT calls to the public
    wrappers an env flip after a shape has been traced triggers a
    retrace instead of being silently ignored (previously the env var
    was read at trace time inside the jit).  Scope: the engine-level
    programs (api._build_counts_jits, tiled's shard_map bodies) wrap
    these calls in their own outer jits and therefore still bake the
    dtype in at THEIR trace time — an engine keeps the operand dtype it
    was built with, and bench's compiled-parity cases keep their
    distinct-pod-bucket spacing for exactly that reason."""
    if operand_dtype is None:
        operand_dtype = os.environ.get("CYCLONUS_PALLAS_DTYPE", "int8")
    if operand_dtype not in ("int8", "bf16"):
        raise ValueError(
            f"CYCLONUS_PALLAS_DTYPE must be int8 or bf16, got {operand_dtype!r}"
        )
    return operand_dtype


def verdict_counts_pallas(
    tmatch_e: jnp.ndarray,  # [T_e, N] bool
    has_e: jnp.ndarray,  # [N] bool
    tallow_e: jnp.ndarray,  # [T_e, N, Q] bf16 (0/1)
    tmatch_i: jnp.ndarray,  # [T_i, N] bool
    has_i: jnp.ndarray,  # [N] bool
    tallow_i: jnp.ndarray,  # [T_i, N, Q] bf16 (0/1)
    n_pods: int | jnp.ndarray = None,
    interpret: bool = False,
    operand_dtype: str = None,
) -> jnp.ndarray:
    """Square (src pods == dst pods) form of verdict_counts_pallas_rect:
    the single-chip counts path.  See the rect docstring for the kernel
    contract."""
    return _verdict_counts_pallas_square(
        tmatch_e, has_e, tallow_e, tmatch_i, has_i, tallow_i,
        n_pods=n_pods if n_pods is not None else tmatch_e.shape[1],
        interpret=interpret,
        operand_dtype=_resolve_operand_dtype(operand_dtype),
    )


@partial(jax.jit, static_argnames=("interpret", "operand_dtype"))
def _verdict_counts_pallas_square(
    tmatch_e, has_e, tallow_e, tmatch_i, has_i, tallow_i,
    n_pods, interpret, operand_dtype,
):
    n = tmatch_e.shape[1]
    valid = jnp.arange(n) < n_pods  # [N] bool
    return _verdict_counts_pallas_rect(
        tmatch_e, has_e, tallow_e, tmatch_i, has_i, tallow_i,
        valid_src=valid, valid_dst=valid, interpret=interpret,
        operand_dtype=operand_dtype,
    )


def verdict_counts_pallas_rect(
    tmatch_e: jnp.ndarray,
    has_e: jnp.ndarray,
    tallow_e: jnp.ndarray,
    tmatch_i: jnp.ndarray,
    has_i: jnp.ndarray,
    tallow_i: jnp.ndarray,
    valid_src: jnp.ndarray = None,
    valid_dst: jnp.ndarray = None,
    interpret: bool = False,
    operand_dtype: str = None,
) -> jnp.ndarray:
    """Public rect entry: resolves the operand dtype eagerly (env or
    argument) and dispatches to the jitted implementation with it as a
    static argument.  See _verdict_counts_pallas_rect for the contract."""
    return _verdict_counts_pallas_rect(
        tmatch_e, has_e, tallow_e, tmatch_i, has_i, tallow_i,
        valid_src=valid_src, valid_dst=valid_dst, interpret=interpret,
        operand_dtype=_resolve_operand_dtype(operand_dtype),
    )


@partial(jax.jit, static_argnames=("interpret", "operand_dtype"))
def _verdict_counts_pallas_rect(
    tmatch_e: jnp.ndarray,  # [T_e, Ns] bool — egress targets vs SRC pods
    has_e: jnp.ndarray,  # [Ns] bool — src pod has an egress target
    tallow_e: jnp.ndarray,  # [T_e, Nd, Q] bf16 (0/1) — egress allows DST
    tmatch_i: jnp.ndarray,  # [T_i, Nd] bool — ingress targets vs DST pods
    has_i: jnp.ndarray,  # [Nd] bool — dst pod has an ingress target
    tallow_i: jnp.ndarray,  # [T_i, Ns, Q] bf16 (0/1) — ingress allows SRC
    valid_src: jnp.ndarray = None,  # [Ns] bool
    valid_dst: jnp.ndarray = None,  # [Nd] bool
    interpret: bool = False,
    operand_dtype: str = "int8",
) -> jnp.ndarray:
    """[Q, n_src_tiles, 3] int32 partial allow counts (ingress, egress,
    combined) over the Ns x Nd x Q grid, without materializing any
    verdict tensor.  Partials are per (port case, src tile) so each stays
    below 2^31; sum them in int64 on the host.

    RECTANGULAR: the src and dst pod axes are independent, which is what
    lets the mesh paths run this kernel per device (src = the device's
    row shard, dst = the full axis or the rotating ring shard).  Validity
    comes in as per-side masks because a shard's rows are a window of the
    global pod axis, not a prefix.

    The allow-if-no-matching-target rule (reference policy.go:158-160)
    and the pod-validity mask are folded into the contraction as ONE
    PSEUDO-TARGET ROW per direction: the pseudo target "matches" exactly
    the valid pods with no real target and "allows" exactly the valid
    pods, so `acc > 0` is the complete verdict and invalid pods come out
    all-False with no per-cell mask arithmetic.  That keeps the per-tile
    epilogue — the VPU-bound floor of this kernel at large N — to two
    compares, one AND, and three reductions.

    Operands ride the MXU as INT8 with int32 accumulation by default:
    exact for 0/1 values, double the bf16 MACs/s on v5e, and half the
    HBM/VMEM per block (bench: 0.27 -> 0.19 s at 100k x 10k, verified
    bit-identical vs bf16 and numpy).  CYCLONUS_PALLAS_DTYPE=bf16
    (resolved by the public wrappers, static here) restores the float
    path."""
    # trace-time side effect on purpose: each increment is one program
    # trace = one compile-cache miss at the jit level (the persistent
    # XLA cache may still serve the binary); dispatches - traces = hits
    ti.KERNEL_TRACES.inc(kernel="counts_rect")
    od = jnp.bfloat16 if operand_dtype == "bf16" else jnp.int8
    ns = tmatch_e.shape[1]
    nd = tmatch_i.shape[1]
    q = tallow_e.shape[2]
    if valid_src is None:
        valid_src = jnp.ones(ns, dtype=bool)
    if valid_dst is None:
        valid_dst = jnp.ones(nd, dtype=bool)

    def _augment(tmatch, has, tallow_qtn, valid_match, valid_allow):
        """Append the pseudo-target row (matches valid no-target pods on
        the MATCH side, allows valid pods on the ALLOW side) and zero the
        invalid-pod columns of BOTH operands: kind-ALL / 0.0.0.0-0 peers
        match EVERY pod including the inert pads the pod axis arrives
        with (shape bucketing pads before the precompute), and an
        unmasked pad column would count as allowed.  tmatch needs the
        mask too — pads match no target, but an arbitrary validity mask
        (the rect contract) may invalidate a REAL pod that a real target
        matches, and that pod's rows must come out all-False, not just
        its columns."""
        va = valid_allow.astype(od)
        vm = valid_match.astype(od)
        pseudo_match = ((~has) & valid_match).astype(od)[None, :]
        tmatch = jnp.concatenate(
            [tmatch.astype(od) * vm[None, :], pseudo_match], axis=0
        )
        tallow_qtn = tallow_qtn * va[None, None, :]
        valid_q = jnp.broadcast_to(va[None, None, :], (q, 1, va.shape[0]))
        tallow_qtn = jnp.concatenate([tallow_qtn, valid_q], axis=1)
        return tmatch, tallow_qtn

    tm_e, tl_e = _augment(
        tmatch_e, has_e, jnp.moveaxis(tallow_e, 2, 0).astype(od),
        valid_src, valid_dst,
    )
    tm_i, tl_i = _augment(
        tmatch_i, has_i, jnp.moveaxis(tallow_i, 2, 0).astype(od),
        valid_dst, valid_src,
    )
    kt_e = _kt_for(tm_e.shape[0])  # tile: 128
    kt_i = _kt_for(tm_i.shape[0])  # tile: 128
    single_chunk = kt_e >= tm_e.shape[0] and kt_i >= tm_i.shape[0]
    bs, bd = _tiles_for(
        kt_e, kt_i, ns,
        single_chunk_int8=single_chunk and od == jnp.int8,
        n_dst=nd,
    )
    # each axis pads to ITS tile size; the per-axis operand PAIRS pad
    # identically (a_e + tl_i share the src axis, b_e + a_i the dst
    # axis), so no view can drop trailing rows of the other
    a_e = _pad_to(_pad_to(tm_e, 0, kt_e), 1, bs).T  # [Ns', T_e']
    a_i = _pad_to(_pad_to(tm_i, 0, kt_i), 1, bd)  # [T_i', Nd']
    b_e = _pad_to(_pad_to(tl_e, 1, kt_e), 2, bd)  # [Q, T_e', Nd']
    b_i = _pad_to(_pad_to(tl_i, 1, kt_i), 2, bs)  # [Q, T_i', Ns']

    ns_pad = a_e.shape[0]
    nd_pad = a_i.shape[1]
    # the k grid dimension is shared, but each direction only has its OWN
    # padded T-chunk count of real work: the kernel skips the other
    # direction's matmul past its n_k (saving the MXU time), and the
    # clamped index maps below keep the block fetch in bounds without
    # padding the shorter direction up (saving the HBM space + DMA)
    n_k_e = b_e.shape[1] // kt_e
    n_k_i = b_i.shape[1] // kt_i

    n_i = ns_pad // bs
    # per-(q, src-tile) partial counts stay within int32: bs * nd_pad
    # allowed cells max per block (raise, not assert — this runtime size
    # guard must survive python -O)
    if bs * nd_pad >= 2**31:
        raise ValueError(
            f"dst axis {nd_pad} too large for int32 tile counts at bs={bs}"
        )
    n_j = nd_pad // bd
    if n_k_e == 1 and n_k_i == 1:
        # single-T-chunk fast path: no cross-k accumulation, so skip the
        # scratch accumulators and the nz/redir skip machinery entirely
        counts = pl.pallas_call(
            _make_verdict_counts_kernel_1chunk(),
            grid=(q, n_i, n_j),
            in_specs=[
                pl.BlockSpec((bs, kt_e), lambda q, i, j: (i, 0)),
                pl.BlockSpec((1, kt_e, bd), lambda q, i, j: (q, 0, j)),
                pl.BlockSpec((1, kt_i, bs), lambda q, i, j: (q, 0, i)),
                pl.BlockSpec((kt_i, bd), lambda q, i, j: (0, j)),
            ],
            out_specs=pl.BlockSpec((1, n_i, 128), lambda q, i, j: (q, 0, 0)),
            scratch_shapes=[pltpu.VMEM((1, 128), jnp.int32)],
            out_shape=jax.ShapeDtypeStruct((q, n_i, 128), jnp.int32),
            cost_estimate=pl.CostEstimate(
                flops=2 * q * ns_pad * nd_pad * (kt_e + kt_i),
                bytes_accessed=2 * q * n_i * nd_pad * (kt_e + kt_i),
                transcendentals=0,
            ),
            interpret=interpret,
        )(a_e, b_e, b_i, a_i)
        return counts[:, :, :3]
    grid = (q, n_i, n_j, max(n_k_e, n_k_i))
    # content maps for the scalar-prefetch skip: which (pod-tile, T-chunk)
    # tmatch blocks hold any nonzero.  O(N*T) device reduction — noise
    # next to the O(N^2 T) matmuls it lets the kernel skip.
    nz_e_mat = (a_e.reshape(n_i, bs, n_k_e, kt_e) != 0).any(axis=(1, 3))  # [n_i, n_k_e]
    nz_i_mat = (a_i.reshape(n_k_i, kt_i, n_j, bd) != 0).any(axis=(1, 3))  # [n_k_i, n_j]

    # DMA-reuse redirects: for a skipped chunk, point every operand's
    # index map at the last USED chunk, so the pallas pipeline sees an
    # unchanged index and fetches nothing (the data is never read — the
    # matmul for that step is skipped by the nz guard).  Without this
    # the skip saves MXU time but the kernel stays HBM-bound fetching
    # blocks it will ignore.
    def _redir(nz, axis):
        n = nz.shape[axis]
        ar = jnp.arange(n, dtype=jnp.int32)
        idx = jnp.where(nz, ar[:, None] if axis == 0 else ar[None, :], -1)
        return jnp.maximum(jax.lax.cummax(idx, axis=axis), 0)

    redir_e = _redir(nz_e_mat, axis=1)  # [n_i, n_k_e]
    redir_i = _redir(nz_i_mat, axis=0)  # [n_k_i, n_j]

    nz_e = nz_e_mat.reshape(-1).astype(jnp.int32)
    nz_i = nz_i_mat.reshape(-1).astype(jnp.int32)
    redir_e = redir_e.reshape(-1)
    redir_i = redir_i.reshape(-1)

    acc_dt = jnp.int32 if od == jnp.int8 else jnp.float32
    clamp_e = lambda k: jnp.minimum(k, n_k_e - 1)
    clamp_i = lambda k: jnp.minimum(k, n_k_i - 1)
    re_ = lambda i, k, redir_e_ref: redir_e_ref[i * n_k_e + clamp_e(k)]
    ri_ = lambda j, k, redir_i_ref: redir_i_ref[clamp_i(k) * n_j + j]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (bs, kt_e), lambda q, i, j, k, ne, ni, re, ri: (i, re_(i, k, re))
            ),
            pl.BlockSpec(
                (1, kt_e, bd),
                lambda q, i, j, k, ne, ni, re, ri: (q, re_(i, k, re), j),
            ),
            pl.BlockSpec(
                (1, kt_i, bs),
                lambda q, i, j, k, ne, ni, re, ri: (q, ri_(j, k, ri), i),
            ),
            pl.BlockSpec(
                (kt_i, bd), lambda q, i, j, k, ne, ni, re, ri: (ri_(j, k, ri), j)
            ),
        ],
        out_specs=pl.BlockSpec((1, n_i, 128), lambda q, i, j, k, *_: (q, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((bs, bd), acc_dt),
            pltpu.VMEM((bs, bd), acc_dt),
            pltpu.VMEM((1, 128), jnp.int32),
        ],
    )
    counts = pl.pallas_call(
        _make_verdict_counts_kernel(n_k_e, n_k_i),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((q, n_i, 128), jnp.int32),
        # deliberate WORST-CASE (dense) cost: the nz-skip fraction is
        # runtime data, and CostEstimate must be static — an upper bound
        # keeps the scheduler conservative rather than starving the
        # pipeline on the dense-tmatch (unsorted/adversarial) case
        cost_estimate=pl.CostEstimate(
            flops=2 * q * ns_pad * nd_pad * (n_k_e * kt_e + n_k_i * kt_i),
            bytes_accessed=2
            * q
            * n_i
            * nd_pad
            * (n_k_e * kt_e + n_k_i * kt_i),
            transcendentals=0,
        ),
        interpret=interpret,
    )(nz_e, nz_i, redir_e, redir_i, a_e, b_e, b_i, a_i)
    # [Q, n_i, 3] int32 partials; the caller sums them in numpy int64
    # (jnp int64 silently truncates to int32 without jax_enable_x64)
    return counts[:, :, :3]


def _should_interpret() -> bool:
    return jax.default_backend() != "tpu"


# --- bit-packed kernel (docs/DESIGN.md "Bit-packed kernel") ---------------
#
# The verdict contraction is pure boolean, so the target axis packs
# 32-per-int32-word (encoding.pack_bool_words): any_allow becomes an OR
# over ceil(T/32) word AND steps instead of a depth-T matmul — a 32x cut
# of the contraction depth and a 16x cut of the dominant operand bytes
# vs bf16.  The whole packed depth fits ONE block at any realistic
# target count (W <= 33 words for T <= 1024), so the kernel is always
# single-chunk: word steps unroll statically and the matmul results
# never leave registers before the epilogue.
#
# The contraction here is the popcount-style word form on the VPU — the
# ISSUE's int8 MXU alternative is the existing dense int8 kernel, which
# stays available as the CYCLONUS_PACK=0 dtype plan; the persisted
# autotuner (engine/autotune.py) picks per shape bucket.
#
# FUSED EPILOGUES: the same body optionally resolves the precedence-
# tier lattice (min-key first-match over scalar-prefetched rule keys —
# previously only the XLA tile loop could evaluate tiered counts, with
# the [c, A, B, Q] tier intermediates round-tripping HBM) and/or the
# class-compression gather's dst-weighted row sums (previously a
# separate einsum over materialized verdict blocks).  Everything stays
# in VMEM between the contraction and the reduction.
#
# Layout rule of thumb: SRC-side per-pod operands put pods on the
# SUBLANE axis and the packed-word/rule axis on the LANE axis
# (128-rounded via lane_round_up, shapelint SC004); DST-side operands
# put pods on the LANE axis.  Both slice [.., w:w+1] / [w:w+1, ..]
# with STATIC w, so no dynamic relayouts reach Mosaic.  Per-side has/
# valid flags ride ONE extra int32 word appended past the packed depth
# (bit 0 = has_target, bit 1 = valid); the matching position of the
# OTHER operand is structural zero padding, so the contraction loop —
# which unrolls only the real words — never sees them.

#: packed-kernel default tile heights (src x dst); the persisted
#: autotuner searches over _PACKED_TILE_CANDIDATES per shape bucket
PACKED_BS = 512
PACKED_BD = 512

#: the packed tile search space (engine/autotune.py candidates): every
#: entry is bounded by the int32 partial-count rule bs * Nd' < 2^31,
#: re-checked at call time
PACKED_TILE_CANDIDATES = ((512, 512), (1024, 512), (2048, 1024))

#: fused-tier unroll ceiling: the min-key loop unrolls statically over
#: the bucketed rule rows, so a pathological ANP set must fall back to
#: the XLA tile loop instead of tracing an unbounded program
PACKED_TIER_MAX_ROWS = 1024


def _sub8(n: int) -> int:
    """Round up to the int32/f32 sublane tile (8)."""
    return -(-max(int(n), 1) // 8) * 8


def _sub32(n: int) -> int:
    """Round up to the int8 sublane tile (32)."""
    return -(-max(int(n), 1) // 32) * 32


def _make_packed_kernel(
    n_w_e: int, n_w_i: int, g_e: int, g_i: int, tiered: bool, weighted: bool
):
    """Packed single-chunk kernel body, specialized on the per-direction
    word depths, the tier rule-row counts, and the epilogue variant.
    Word and rule loops unroll statically (n_w <= ~33; g bounded by
    PACKED_TIER_MAX_ROWS at the eligibility gate)."""
    ti.KERNEL_TRACES.inc(
        kernel="counts_packed"
        + ("_tiered" if tiered else "")
        + ("_weighted" if weighted else "")
    )
    from .encoding import TIER_KEY_NONE

    def _kernel(*refs):
        idx = 0
        if tiered:
            anp_e_ref, banp_e_ref, anp_i_ref, banp_i_ref = refs[:4]
            idx = 4
        a_e_ref = refs[idx]  # [BS, We_l] i32 — tmatch_e^T words + flags col
        b_e_ref = refs[idx + 1]  # [1, We_s, BD] i32 — tallow_e words
        b_i_ref = refs[idx + 2]  # [1, BS, Wi_l] i32 — tallow_i^T words
        a_i_ref = refs[idx + 3]  # [Wi_s, BD] i32 — tmatch_i words + flags row
        idx += 4
        if tiered:
            subj_e_ref = refs[idx]  # [BS, Ge_l] i8
            peerq_e_ref = refs[idx + 1]  # [1, Ge_s, BD] i8
            subj_i_ref = refs[idx + 2]  # [Gi_s, BD] i8
            peerq_i_ref = refs[idx + 3]  # [1, BS, Gi_l] i8
            idx += 4
        if weighted:
            w_ref = refs[idx]  # [8, BD] f32 (row 0 real)
            idx += 1
        out_ref = refs[idx]
        acc_ref = refs[idx + 1]  # weighted: [BS, 128] f32; counts: [1, 128] i32

        i = pl.program_id(1)
        j = pl.program_id(2)
        n_j = pl.num_programs(2)

        if not weighted:
            @pl.when((i == 0) & (j == 0))
            def _init_out():
                out_ref[:] = jnp.zeros_like(out_ref)

        @pl.when(j == 0)
        def _init_acc():
            acc_ref[:] = jnp.zeros_like(acc_ref)

        # word-packed contraction, fully unrolled: the OR-accumulators
        # live in registers straight into the epilogue
        acc_e = a_e_ref[:, 0:1] & b_e_ref[0, 0:1, :]  # [BS, BD] i32
        for w in range(1, n_w_e):
            acc_e = acc_e | (a_e_ref[:, w : w + 1] & b_e_ref[0, w : w + 1, :])
        acc_i = b_i_ref[0, :, 0:1] & a_i_ref[0:1, :]
        for w in range(1, n_w_i):
            acc_i = acc_i | (b_i_ref[0, :, w : w + 1] & a_i_ref[w : w + 1, :])

        # per-side flags ride one extra word past the packed depth
        flags_s = a_e_ref[:, n_w_e : n_w_e + 1]  # [BS, 1] i32
        flags_d = a_i_ref[n_w_i : n_w_i + 1, :]  # [1, BD] i32
        has_s = (flags_s & 1) != 0
        valid_s = (flags_s & 2) != 0
        has_d = (flags_d & 1) != 0
        valid_d = (flags_d & 2) != 0

        egress = (~has_s) | (acc_e != 0)  # [BS, BD]
        ingress = (~has_d) | (acc_i != 0)

        if tiered:
            # fused tier min-key first-match epilogue: the same fold as
            # kernel.tier_first_match_keys, with rule keys read from
            # scalar prefetch and the [g, BS, BD] intermediates never
            # leaving registers (the HBM round trip this fusion kills)
            none = jnp.int32(TIER_KEY_NONE)
            anp_e = jnp.full(egress.shape, none, dtype=jnp.int32)
            banp_e = jnp.full(egress.shape, none, dtype=jnp.int32)
            for g in range(g_e):
                m = (subj_e_ref[:, g : g + 1] & peerq_e_ref[0, g : g + 1, :]) != 0
                anp_e = jnp.minimum(anp_e, jnp.where(m, anp_e_ref[g], none))
                banp_e = jnp.minimum(banp_e, jnp.where(m, banp_e_ref[g], none))
            egress = resolve_tier_lattice_packed(egress, has_s, anp_e, banp_e)
            anp_i = jnp.full(ingress.shape, none, dtype=jnp.int32)
            banp_i = jnp.full(ingress.shape, none, dtype=jnp.int32)
            for g in range(g_i):
                # ingress subjects are the DST pods, peers the SRC pods
                m = (peerq_i_ref[0, :, g : g + 1] & subj_i_ref[g : g + 1, :]) != 0
                anp_i = jnp.minimum(anp_i, jnp.where(m, anp_i_ref[g], none))
                banp_i = jnp.minimum(banp_i, jnp.where(m, banp_i_ref[g], none))
            ingress = resolve_tier_lattice_packed(ingress, has_d, anp_i, banp_i)

        combined = egress & ingress

        if weighted:
            # fused class-compression gather epilogue: dst-weighted row
            # sums (tiled._class_tile_rowsums' einsum) computed in VMEM.
            # Full-f32 VPU multiply-accumulate — exact for integer row
            # sums < 2^24, the same bound the split path's HIGHEST-
            # precision einsum holds (pad classes carry weight 0).
            wrow = w_ref[0:1, :]  # [1, BD] f32
            lane = jax.lax.broadcasted_iota(jnp.int32, (1, 128), 1)
            rs = (
                jnp.where(
                    lane == 0,
                    jnp.sum(ingress.astype(jnp.float32) * wrow, axis=1,
                            keepdims=True),
                    0.0,
                )
                + jnp.where(
                    lane == 1,
                    jnp.sum(egress.astype(jnp.float32) * wrow, axis=1,
                            keepdims=True),
                    0.0,
                )
                + jnp.where(
                    lane == 2,
                    jnp.sum(combined.astype(jnp.float32) * wrow, axis=1,
                            keepdims=True),
                    0.0,
                )
            )  # [BS, 128]
            acc_ref[:] += rs

            @pl.when(j == n_j - 1)
            def _flush_rs():
                out_ref[:] = acc_ref[:].reshape(1, *acc_ref.shape)
        else:
            mask = valid_s & valid_d
            c_in = jnp.sum((ingress & mask).astype(jnp.int32))
            c_eg = jnp.sum((egress & mask).astype(jnp.int32))
            c_co = jnp.sum((combined & mask).astype(jnp.int32))
            lane = jax.lax.broadcasted_iota(jnp.int32, (1, 128), 1)
            acc_ref[:] += (
                jnp.where(lane == 0, c_in, 0)
                + jnp.where(lane == 1, c_eg, 0)
                + jnp.where(lane == 2, c_co, 0)
            )

            @pl.when(j == n_j - 1)
            def _flush():
                out_ref[:, pl.ds(i, 1), :] = acc_ref[:].reshape(1, 1, 128)

    return _kernel


def resolve_tier_lattice_packed(np_allowed, has_b, anp_min, banp_min):
    """The tier lattice fold, kernel-local twin of
    kernel.resolve_tier_lattice (pure jnp, safe inside a Pallas body;
    re-implemented here to keep this module import-light and the
    constants explicit).  Bit-identity with the XLA fold is pinned by
    the fused-vs-split parity tests."""
    from .encoding import (
        TIER_ACT_ALLOW,
        TIER_ACT_NONE,
        TIER_ACT_PASS,
        TIER_KEY_NONE,
    )

    anp_act = jnp.where(anp_min < TIER_KEY_NONE, anp_min % 4, TIER_ACT_NONE)
    banp_act = jnp.where(banp_min < TIER_KEY_NONE, banp_min % 4, TIER_ACT_NONE)
    below = jnp.where(
        has_b,
        np_allowed,
        jnp.where(
            banp_act == TIER_ACT_NONE, True, banp_act == TIER_ACT_ALLOW
        ),
    )
    return jnp.where(
        (anp_act == TIER_ACT_NONE) | (anp_act == TIER_ACT_PASS),
        below,
        anp_act == TIER_ACT_ALLOW,
    )


def packed_tier_eligible(tensors: Dict) -> bool:
    """THE host-side gate for the fused tier epilogue — the min-key
    loop unrolls statically over the bucketed rule rows, so an
    adversarial rule count must route to the XLA tile loop instead.
    One implementation on purpose: both the dense counts route
    (api._packed_tier_ok) and the fused class-counts route
    (tiled.evaluate_grid_counts_classes) consult it, so the ceiling
    cannot drift between them.  `tensors` is an engine tensor dict
    (the bucketed tier action slabs carry the row counts)."""
    if "tiers" not in tensors:
        return True
    rows = sum(
        int(tensors["tiers"][d]["action"].shape[0])
        for d in ("ingress", "egress")
    )
    return rows <= PACKED_TIER_MAX_ROWS


def verdict_counts_pallas_packed(
    tmatch_e_pk: jnp.ndarray,  # [We, Ns] int32 — packed egress tmatch
    has_e: jnp.ndarray,  # [Ns] bool
    tallow_e_pk: jnp.ndarray,  # [We, Nd, Q] int32 — packed egress tallow
    tmatch_i_pk: jnp.ndarray,  # [Wi, Nd] int32
    has_i: jnp.ndarray,  # [Nd] bool
    tallow_i_pk: jnp.ndarray,  # [Wi, Ns, Q] int32
    n_pods: int | jnp.ndarray = None,
    valid_src: jnp.ndarray = None,
    valid_dst: jnp.ndarray = None,
    tier: Dict = None,
    w_dst: jnp.ndarray = None,
    bs: int = None,
    bd: int = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """The packed verdict kernel over pre-packed operands
    (tiled._precompute(pack=True)).

    Returns [Q, n_src_tiles, 3] int32 partial counts, or — with `w_dst`
    (the class-size weights of the fused class-compression gather) —
    [Q, Ns_pad, 3] f32 dst-weighted row sums.  `tier` fuses the
    precedence-tier min-key epilogue ({direction: {subj, peerq,
    anp_key, banp_key}} from the tiled precompute).  RECTANGULAR like
    verdict_counts_pallas_rect: per-side validity masks, so the mesh
    paths run it per device shard.  Semantics mirror the XLA tile body
    exactly (explicit ~has OR and validity-masked counts — no
    pseudo-target row), which is what the packed parity suite pins."""
    ns = tmatch_e_pk.shape[1]
    nd = tmatch_i_pk.shape[1]
    if valid_src is None:
        n32 = ns if n_pods is None else n_pods
        valid_src = jnp.arange(ns) < n32
    if valid_dst is None:
        n32 = nd if n_pods is None else n_pods
        valid_dst = jnp.arange(nd) < n32
    return _verdict_counts_pallas_packed(
        tmatch_e_pk, has_e, tallow_e_pk, tmatch_i_pk, has_i, tallow_i_pk,
        valid_src, valid_dst, tier, w_dst,
        bs=bs if bs is not None else PACKED_BS,
        bd=bd if bd is not None else PACKED_BD,
        interpret=interpret,
    )


@partial(jax.jit, static_argnames=("bs", "bd", "interpret"))
def _verdict_counts_pallas_packed(
    tmatch_e_pk, has_e, tallow_e_pk, tmatch_i_pk, has_i, tallow_i_pk,
    valid_src, valid_dst, tier, w_dst, bs, bd, interpret,
):
    we = tmatch_e_pk.shape[0]
    wi = tmatch_i_pk.shape[0]
    q = tallow_e_pk.shape[2]

    # mask invalid pod columns out of every packed operand (an arbitrary
    # rect validity mask may invalidate REAL pods, and a pad column must
    # contribute nothing to either contraction)
    vs = valid_src[None, :]
    vd = valid_dst[None, :]
    tm_e = jnp.where(vs, tmatch_e_pk, 0)
    tm_i = jnp.where(vd, tmatch_i_pk, 0)
    tl_e = jnp.where(vd[:, :, None], tallow_e_pk, 0)
    tl_i = jnp.where(vs[:, :, None], tallow_i_pk, 0)

    # per-side flags words (bit 0 = has_target, bit 1 = valid)
    flags_s = has_e.astype(jnp.int32) + 2 * valid_src.astype(jnp.int32)
    flags_d = has_i.astype(jnp.int32) + 2 * valid_dst.astype(jnp.int32)

    we_l = lane_round_up(we + 1)  # tile: 128 — flags col at index we
    wi_l = lane_round_up(wi)  # tile: 128
    we_s = _sub8(we)
    wi_s = _sub8(wi + 1)  # flags row at index wi

    a_e = jnp.concatenate([tm_e.T, flags_s[:, None]], axis=1)  # [Ns, We+1]
    a_e = _pad_to(_pad_to(a_e, 1, we_l), 0, bs)  # [Ns', We_l]
    b_e = _pad_to(
        _pad_to(jnp.moveaxis(tl_e, 2, 0), 1, we_s), 2, bd
    )  # [Q, We_s, Nd']
    b_i = _pad_to(
        _pad_to(jnp.transpose(tl_i, (2, 1, 0)), 1, bs), 2, wi_l
    )  # [Q, Ns', Wi_l]
    a_i = jnp.concatenate([tm_i, flags_d[None, :]], axis=0)  # [Wi+1, Nd]
    a_i = _pad_to(_pad_to(a_i, 0, wi_s), 1, bd)  # [Wi_s, Nd']

    ns_pad = a_e.shape[0]
    nd_pad = a_i.shape[1]
    n_i = ns_pad // bs
    n_j = nd_pad // bd
    if bs * nd_pad >= 2**31:
        raise ValueError(
            f"dst axis {nd_pad} too large for int32 tile counts at bs={bs}"
        )

    # structure, not value: jit retraces per pytree structure, so the
    # None checks are static at trace time
    tiered = tier is not None  # jaxlint: ignore[JX002]
    weighted = w_dst is not None  # jaxlint: ignore[JX002]
    g_e = int(tier["egress"]["subj"].shape[0]) if tiered else 0  # jaxlint: ignore[JX002]
    g_i = int(tier["ingress"]["subj"].shape[0]) if tiered else 0  # jaxlint: ignore[JX002]

    # (block shape, plain (q, i, j) index map) pairs; materialized as
    # BlockSpecs per grid-spec flavor below (the prefetch flavor's maps
    # take trailing scalar refs the packed maps ignore)
    operands = [a_e, b_e, b_i, a_i]
    blocks = [
        ((bs, we_l), lambda q, i, j: (i, 0)),
        ((1, we_s, bd), lambda q, i, j: (q, 0, j)),
        ((1, bs, wi_l), lambda q, i, j: (q, i, 0)),
        ((wi_s, bd), lambda q, i, j: (0, j)),
    ]
    prefetch = []
    if tiered:  # jaxlint: ignore[JX002] — static structure branch
        te, ti_ = tier["egress"], tier["ingress"]
        ge_l = lane_round_up(g_e)  # tile: 128
        ge_s = _sub32(g_e)
        gi_l = lane_round_up(g_i)  # tile: 128
        gi_s = _sub32(g_i)
        subj_e = _pad_to(
            _pad_to(
                jnp.where(vs, te["subj"], False).T.astype(jnp.int8), 1, ge_l
            ),
            0,
            bs,
        )  # [Ns', Ge_l]
        peerq_e = _pad_to(
            _pad_to(
                jnp.moveaxis(
                    (te["peerq"] & vd[:, :, None]).astype(jnp.int8), 2, 0
                ),
                1,
                ge_s,
            ),
            2,
            bd,
        )  # [Q, Ge_s, Nd']
        subj_i = _pad_to(
            _pad_to(
                jnp.where(vd, ti_["subj"], False).astype(jnp.int8), 0, gi_s
            ),
            1,
            bd,
        )  # [Gi_s, Nd']
        peerq_i = _pad_to(
            _pad_to(
                jnp.transpose(
                    (ti_["peerq"] & vs[:, :, None]).astype(jnp.int8),
                    (2, 1, 0),
                ),
                1,
                bs,
            ),
            2,
            gi_l,
        )  # [Q, Ns', Gi_l]
        operands += [subj_e, peerq_e, subj_i, peerq_i]
        blocks += [
            ((bs, ge_l), lambda q, i, j: (i, 0)),
            ((1, ge_s, bd), lambda q, i, j: (q, 0, j)),
            ((gi_s, bd), lambda q, i, j: (0, j)),
            ((1, bs, gi_l), lambda q, i, j: (q, i, 0)),
        ]
        prefetch = [
            te["anp_key"].astype(jnp.int32),
            te["banp_key"].astype(jnp.int32),
            ti_["anp_key"].astype(jnp.int32),
            ti_["banp_key"].astype(jnp.int32),
        ]
    if weighted:  # jaxlint: ignore[JX002] — static structure branch
        w8 = jnp.zeros((8, nd_pad), dtype=jnp.float32)
        w8 = w8.at[0, : w_dst.shape[0]].set(w_dst.astype(jnp.float32))
        operands.append(w8)
        blocks.append(((8, bd), lambda q, i, j: (0, j)))

    kernel = _make_packed_kernel(we, wi, g_e, g_i, tiered, weighted)
    if weighted:  # jaxlint: ignore[JX002] — static structure branch
        out_block = ((1, bs, 128), lambda q, i, j: (q, i, 0))
        out_shape = jax.ShapeDtypeStruct((q, ns_pad, 128), jnp.float32)
        scratch = [pltpu.VMEM((bs, 128), jnp.float32)]
    else:
        out_block = ((1, n_i, 128), lambda q, i, j: (q, 0, 0))
        out_shape = jax.ShapeDtypeStruct((q, n_i, 128), jnp.int32)
        scratch = [pltpu.VMEM((1, 128), jnp.int32)]
    cost = pl.CostEstimate(
        flops=2 * q * ns_pad * nd_pad * (we + wi + g_e + g_i + 3),
        bytes_accessed=4 * q * n_i * (bs * we_l + nd_pad * (we_s + wi_s))
        + 4 * q * n_i * bs * wi_l,
        transcendentals=0,
    )
    if tiered:  # jaxlint: ignore[JX002] — static structure branch

        def _with_prefetch(m):
            # grid indices first, then one ref per scalar-prefetch
            # operand (ignored by the packed maps)
            return lambda q, i, j, *refs, _m=m: _m(q, i, j)

        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(q, n_i, n_j),
            in_specs=[
                pl.BlockSpec(shape, _with_prefetch(m)) for shape, m in blocks
            ],
            out_specs=pl.BlockSpec(out_block[0], _with_prefetch(out_block[1])),
            scratch_shapes=scratch,
        )
        out = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=out_shape,
            cost_estimate=cost,
            interpret=interpret,
        )(*prefetch, *operands)
    else:
        out = pl.pallas_call(
            kernel,
            grid=(q, n_i, n_j),
            in_specs=[pl.BlockSpec(shape, m) for shape, m in blocks],
            out_specs=pl.BlockSpec(*out_block),
            scratch_shapes=scratch,
            out_shape=out_shape,
            cost_estimate=cost,
            interpret=interpret,
        )(*operands)
    return out[:, :, :3]


# --- per-tile target slabs -------------------------------------------------
#
# The single-chunk kernel contracts EVERY tile pair over the full live
# target depth (kt_e + kt_i, ~640 at the 100k x 10k bench), but with
# pods and targets namespace-sorted a 2048-pod src tile only ever
# matches a narrow contiguous band of targets (~5-10 rows at the bench
# shape: a target applies to pods of exactly one namespace,
# kernel.direction_precompute).  The slab path gathers, per pod tile,
# one fixed-width window (SLAB_W rows) of the target axis covering that
# band — for BOTH directions — so the per-step contraction depth drops
# from kt_e + kt_i to 2 * SLAB_W regardless of the policy count.  The
# no-matching-target rule and the validity mask cannot ride the matmul
# anymore (the pseudo row lives outside most windows), so they move to
# the epilogue as two VPU OR-terms per direction, fed by four small
# per-tile vectors.
#
# Eligibility is decided HOST-side (slab_windows on a numpy tmatch
# twin): every tile's nonzero target rows must fit one SLAB_W window.
# Ns-sorted clusters qualify overwhelmingly; anything else falls back
# to the single/multi-chunk kernels.  r3 measured a 256-aligned
# windowing of the INGRESS direction only at ~10-15% — consistent with
# depth 640 -> 512; this path targets depth -> 256.

SLAB_W = 128
SLAB_BS = 2048
SLAB_BD = 1024


def slab_w_aug(operand_dtype: str = None, w: int = None) -> int:
    """Augmented window depth the slab kernel actually materializes:
    the w-row window + the pseudo/validity OR-term row, ROUNDED UP to
    the operand dtype's native sublane tile (int8: 32, bf16: 16).  The
    ceil keeps the alignment property for ARBITRARY w overrides (the
    old `w + tile` form only aligned when w itself was tile-aligned);
    for tile-aligned w the two forms agree, so the default layout is
    unchanged.  The ONE source of truth — the engine's HBM budget
    (api._slab_plan) must use this, not re-derive it."""
    if w is None:
        w = SLAB_W
    od = _resolve_operand_dtype(operand_dtype)
    tile = 32 if od == "int8" else 16
    return -(-(w + 1) // tile) * tile


def slab_windows(tmatch: "np.ndarray", tile: int, w: int = SLAB_W):
    """Per-tile target-window starts from a HOST (numpy, valid-masked)
    tmatch [T, N]: returns (t0 [n_tiles] int32, ok).  ok is False when
    any tile's nonzero rows span more than w — the caller must then use
    the non-slab kernels.  Empty tiles get t0 = 0 (their tmatch slab is
    all zero, so the window content is irrelevant)."""
    import numpy as np

    t, n = tmatch.shape
    n_tiles = -(-n // tile) if n else 0
    if n_tiles == 0 or t == 0:
        return np.zeros(max(n_tiles, 1), dtype=np.int32), True
    pad = n_tiles * tile - n
    if pad:
        tmatch = np.pad(tmatch, ((0, 0), (0, pad)))
    nz = tmatch.reshape(t, n_tiles, tile).any(axis=2)  # [T, n_tiles]
    any_t = nz.any(axis=0)
    first = np.where(any_t, nz.argmax(axis=0), 0).astype(np.int32)
    last = np.where(any_t, t - 1 - nz[::-1].argmax(axis=0), -1)
    ok = bool(((last - first) < w).all())
    return first, ok


def _make_verdict_counts_kernel_slab():
    """Kernel body for the slab path: one matmul per direction over the
    tile's augmented target window (values straight into the count
    epilogue, exactly like the 1chunk kernel).  The pseudo/validity
    OR-terms ride INSIDE the window as one augmented row per direction
    (appended at gather time by _verdict_counts_pallas_slab), so
    `acc > 0` is the complete verdict.  An epilogue formulation was
    tried and does not survive Mosaic: i1 minor-dim inserts
    (`pe[:, None]`) are unsupported, 1-D int32 relayouts crash layout
    inference, and rank-1 dot_general OR-terms blow the 16 MB scoped
    VMEM stack at the (2048, 1024) tile."""
    ti.KERNEL_TRACES.inc(kernel="counts_slab")

    def _kernel(
        a_e_ref,  # [1, Wa, BS] od — tmatch_e window+pseudo row, src tile i
        b_e_ref,  # [1, 1, Wa, BD] od — tallow_e window+valid row (q, i, j)
        b_i_ref,  # [1, 1, Wa, BS] od — tallow_i window+valid row (q, j, i)
        a_i_ref,  # [1, Wa, BD] od — tmatch_i window+pseudo row, dst tile j
        counts_ref,  # [1, n_i, 128] int32 per-q count plane
        cnt_ref,  # [1, 128] int32 scratch
    ):
        i = pl.program_id(1)
        j = pl.program_id(2)
        n_j = pl.num_programs(2)

        @pl.when((i == 0) & (j == 0))
        def _init_counts():
            counts_ref[:] = jnp.zeros_like(counts_ref)

        @pl.when(j == 0)
        def _init_cnt():
            cnt_ref[:] = jnp.zeros_like(cnt_ref)

        acc_dt = jnp.int32 if a_e_ref.dtype == jnp.int8 else jnp.float32
        # egress[s, d] = sum_w tmatch_e[w, s] * tallow_e[w, d]
        acc_e = jax.lax.dot_general(
            a_e_ref[0],
            b_e_ref[0, 0],
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=acc_dt,
        )
        # ingress[s, d] = sum_w tallow_i[w, s] * tmatch_i[w, d]
        acc_i = jax.lax.dot_general(
            b_i_ref[0, 0],
            a_i_ref[0],
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=acc_dt,
        )
        zero = jnp.array(0, acc_dt)
        egress = acc_e > zero
        ingress = acc_i > zero
        combined = egress & ingress
        c_in = jnp.sum(ingress.astype(jnp.int32))
        c_eg = jnp.sum(egress.astype(jnp.int32))
        c_co = jnp.sum(combined.astype(jnp.int32))
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, 128), 1)
        cnt_ref[:] += (
            jnp.where(lane == 0, c_in, 0)
            + jnp.where(lane == 1, c_eg, 0)
            + jnp.where(lane == 2, c_co, 0)
        )

        @pl.when(j == n_j - 1)
        def _flush():
            counts_ref[:, pl.ds(i, 1), :] = cnt_ref[:].reshape(1, 1, 128)

    return _kernel


def verdict_counts_pallas_slab(
    tmatch_e: jnp.ndarray,  # [T_e, N] bool
    has_e: jnp.ndarray,  # [N] bool
    tallow_e: jnp.ndarray,  # [T_e, N, Q] bf16 (0/1)
    tmatch_i: jnp.ndarray,  # [T_i, N] bool
    has_i: jnp.ndarray,  # [N] bool
    tallow_i: jnp.ndarray,  # [T_i, N, Q] bf16 (0/1)
    t0_e: jnp.ndarray,  # [n_i] int32 window starts (host: slab_windows)
    t0_i: jnp.ndarray,  # [n_j] int32
    n_pods: int | jnp.ndarray,
    interpret: bool = False,
    operand_dtype: str = None,
    bs: int = None,
    bd: int = None,
    w: int = None,
) -> jnp.ndarray:
    """[Q, n_i, 3] int32 partial counts via per-tile target slabs.  The
    caller guarantees (via slab_windows on the SAME valid-masked tmatch,
    with the SAME w) that every tile's nonzero target rows fit its w
    window; violations silently undercount, which is why eligibility is
    checked host-side with the identical reduction.  All three layout
    defaults resolve from the module globals at CALL time so a runtime
    override (tests monkeypatch them) can never desynchronize the host
    check from the kernel's actual window.

    Design note: the slabs are MATERIALIZED per-tile gathers — [q,
    n_tiles, w_aug, N] in HBM — which caps this path at ~150k pods (the
    caller gates on the byte estimate).  This composed form rebuilds
    them per dispatch; steady-state callers should build them once with
    slab_operands and dispatch verdict_counts_pallas_slab_from_ops
    (r5 measured the rebuild at more than the depth cut's savings).
    The alternative (scalar-prefetch block maps into the original
    arrays, like the general kernel's nz redirects) avoids the copies
    and the cap, but block index maps are w-ALIGNED, so covering an
    arbitrary <=w/2-wide span needs a 2-block window — doubling the
    contraction depth and giving back most of the win at the 100k bench
    shape."""
    return _verdict_counts_pallas_slab(
        tmatch_e, has_e, tallow_e, tmatch_i, has_i, tallow_i,
        t0_e, t0_i, n_pods,
        interpret=interpret,
        operand_dtype=_resolve_operand_dtype(operand_dtype),
        bs=bs if bs is not None else SLAB_BS,
        bd=bd if bd is not None else SLAB_BD,
        w=w if w is not None else SLAB_W,
    )


def slab_operands(
    tmatch_e, has_e, tallow_e, tmatch_i, has_i, tallow_i,
    t0_e, t0_i, n_pods, operand_dtype=None, bs=None, bd=None, w=None,
):
    """The slab path's gathered operands — {a_e, b_e, b_i, a_i} — as a
    SEPARATE traceable stage: they depend only on the precompute and the
    (fixed) window starts, so a steady-state caller can materialize them
    ONCE and cache them device-resident next to the precompute.  Round-5
    measurement: rebuilding these per dispatch (the original fused form)
    cost more than the slab's depth cut saved, flipping the kernel from
    a ~2x device-time win to a 22% loss."""
    return _slab_operands(
        tmatch_e, has_e, tallow_e, tmatch_i, has_i, tallow_i,
        t0_e, t0_i, n_pods,
        operand_dtype=_resolve_operand_dtype(operand_dtype),
        bs=bs if bs is not None else SLAB_BS,
        bd=bd if bd is not None else SLAB_BD,
        w=w if w is not None else SLAB_W,
    )


@partial(
    jax.jit, static_argnames=("operand_dtype", "bs", "bd", "w")
)
def _slab_operands(
    tmatch_e, has_e, tallow_e, tmatch_i, has_i, tallow_i,
    t0_e, t0_i, n_pods, operand_dtype, bs, bd, w,
):
    od = jnp.bfloat16 if operand_dtype == "bf16" else jnp.int8
    n = tmatch_e.shape[1]
    q = tallow_e.shape[2]
    valid = (jnp.arange(n) < n_pods).astype(od)  # [N]

    ns_pad = -(-max(n, 1) // bs) * bs
    nd_pad = -(-max(n, 1) // bd) * bd
    n_i, n_j = ns_pad // bs, nd_pad // bd
    if bs * nd_pad >= 2**31:
        raise ValueError(
            f"dst axis {nd_pad} too large for int32 tile counts at bs={bs}"
        )

    def prep(tmatch, tallow, valid_match, valid_allow, n_pad_match, n_pad_allow):
        """Valid-masked, od-cast, pod-padded operands plus a w-padded
        target axis so every dynamic window slice is in bounds."""
        tm = tmatch.astype(od) * valid_match[None, :]
        tl = jnp.moveaxis(tallow, 2, 0).astype(od) * valid_allow[None, None, :]
        tm = _pad_to(_pad_to(tm, 0, 1), 1, n_pad_match)  # pod pad
        tl = _pad_to(tl, 2, n_pad_allow)
        # target-axis guard: append w zero rows (zero targets match and
        # allow nothing, so an empty tile's window reads only zeros)
        tm = jnp.pad(tm, ((0, w), (0, 0)))
        tl = jnp.pad(tl, ((0, 0), (0, w), (0, 0)))
        return tm, tl

    tm_e, tl_e = prep(tmatch_e, tallow_e, valid, valid, bs, bd)
    tm_i, tl_i = prep(tmatch_i, tallow_i, valid, valid, bd, bs)
    t_e_pad = tm_e.shape[0]
    t_i_pad = tm_i.shape[0]
    t0_e = jnp.clip(t0_e.astype(jnp.int32), 0, t_e_pad - w)
    t0_i = jnp.clip(t0_i.astype(jnp.int32), 0, t_i_pad - w)

    # Augmented window depth: one extra row carries the pseudo/validity
    # OR-term per direction (the kernel is then pure matmul + compare,
    # mirroring the proven 1chunk body), padded to the dtype's native
    # sublane tile so every block fetch stays aligned.
    w_aug = slab_w_aug(operand_dtype, w)

    # slab gathers (per-eval; cacheable with the precompute when the
    # engine's device-resident pre-cache holds)
    def gather_tm(tm, t0, tile, count, pseudo):
        """[count, w_aug, tile]: the w-row window, then the pseudo row
        for this tile's pod columns, then alignment zeros."""

        def one(i, t0i):
            return jax.lax.dynamic_slice(tm, (t0i, i * tile), (w, tile))

        win = jax.vmap(one)(jnp.arange(count), t0)  # [count, w, tile]
        aug = pseudo.reshape(count, 1, tile)
        pad = jnp.zeros((count, w_aug - w - 1, tile), dtype=win.dtype)
        return jnp.concatenate([win, aug, pad], axis=1)

    def gather_tl(tl, t0, vrow_other):
        """[count, q, w_aug, n_other]: window + the valid row (the
        OR-term's allow side) + alignment zeros."""

        def one(t0i):
            return jax.lax.dynamic_slice(
                tl, (0, t0i, 0), (q, w, tl.shape[2])
            )

        win = jax.vmap(one)(t0)  # [count, q, w, n_other]
        count = win.shape[0]
        n_other = win.shape[3]
        aug = jnp.broadcast_to(
            vrow_other[None, None, None, :], (count, q, 1, n_other)
        ).astype(win.dtype)
        pad = jnp.zeros((count, q, w_aug - w - 1, n_other), dtype=win.dtype)
        return jnp.concatenate([win, aug, pad], axis=2)

    pe = ((~has_e) & (jnp.arange(n) < n_pods)).astype(od)  # [N]
    pi = ((~has_i) & (jnp.arange(n) < n_pods)).astype(od)
    pe_s = _pad_to(pe[None, :], 1, bs)[0]  # [ns_pad]
    pi_d = _pad_to(pi[None, :], 1, bd)[0]  # [nd_pad]
    vs = _pad_to(valid[None, :], 1, bs)[0]  # [ns_pad]
    vd = _pad_to(valid[None, :], 1, bd)[0]  # [nd_pad]

    # egress: acc[s, d] += pe[s] * vd[d]; ingress: acc[s, d] += vs[s] * pi[d]
    a_e = gather_tm(tm_e, t0_e, bs, n_i, pe_s)  # [n_i, w_aug, bs]
    a_i = gather_tm(tm_i, t0_i, bd, n_j, pi_d)  # [n_j, w_aug, bd]
    b_e = jnp.moveaxis(gather_tl(tl_e, t0_e, vd), 1, 0)  # [q, n_i, w_aug, nd_pad]
    b_i = jnp.moveaxis(gather_tl(tl_i, t0_i, vs), 1, 0)  # [q, n_j, w_aug, ns_pad]
    return {"a_e": a_e, "b_e": b_e, "b_i": b_i, "a_i": a_i}


def verdict_counts_pallas_slab_from_ops(ops, interpret: bool = False):
    """[Q, n_i, 3] int32 partials from pre-gathered slab operands
    (slab_operands).  Every layout parameter is derived from the operand
    shapes, so cached operands can never desynchronize from the kernel's
    block specs."""
    a_e, b_e, b_i, a_i = ops["a_e"], ops["b_e"], ops["b_i"], ops["a_i"]
    n_i, w_aug, bs = a_e.shape
    n_j, _, bd = a_i.shape
    q = b_e.shape[0]
    ns_pad, nd_pad = n_i * bs, n_j * bd
    counts = pl.pallas_call(
        _make_verdict_counts_kernel_slab(),
        grid=(q, n_i, n_j),
        in_specs=[
            pl.BlockSpec((1, w_aug, bs), lambda q, i, j: (i, 0, 0)),
            pl.BlockSpec((1, 1, w_aug, bd), lambda q, i, j: (q, i, 0, j)),
            pl.BlockSpec((1, 1, w_aug, bs), lambda q, i, j: (q, j, 0, i)),
            pl.BlockSpec((1, w_aug, bd), lambda q, i, j: (j, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, n_i, 128), lambda q, i, j: (q, 0, 0)),
        scratch_shapes=[pltpu.VMEM((1, 128), jnp.int32)],
        out_shape=jax.ShapeDtypeStruct((q, n_i, 128), jnp.int32),
        cost_estimate=pl.CostEstimate(
            flops=2 * q * ns_pad * nd_pad * 2 * w_aug,
            bytes_accessed=q * n_i * n_j * w_aug * (bs + bd),
            transcendentals=0,
        ),
        interpret=interpret,
    )(a_e, b_e, b_i, a_i)
    return counts[:, :, :3]


@partial(
    jax.jit, static_argnames=("interpret", "operand_dtype", "bs", "bd", "w")
)
def _verdict_counts_pallas_slab(
    tmatch_e, has_e, tallow_e, tmatch_i, has_i, tallow_i,
    t0_e, t0_i, n_pods, interpret, operand_dtype, bs, bd, w,
):
    ops = _slab_operands(
        tmatch_e, has_e, tallow_e, tmatch_i, has_i, tallow_i,
        t0_e, t0_i, n_pods,
        operand_dtype=operand_dtype, bs=bs, bd=bd, w=w,
    )
    return verdict_counts_pallas_slab_from_ops(ops, interpret=interpret)


def sum_partials(partials, q: int, n_pods: int) -> Dict[str, int]:
    """Host-side int64 reduction of [Q, n_tiles, 3] partials into the
    counts dict — the ONE place that knows the lane order (ingress,
    egress, combined).  jnp int64 silently truncates without
    jax_enable_x64, hence numpy."""
    import numpy as np

    c = np.asarray(partials, dtype=np.int64).sum(axis=(0, 1))
    return {
        "ingress": int(c[0]),
        "egress": int(c[1]),
        "combined": int(c[2]),
        "cells": q * n_pods * n_pods,
    }


def evaluate_grid_counts_pallas(tensors: Dict, n_pods: int) -> Dict[str, int]:
    """Drop-in alternative to tiled.evaluate_grid_counts riding the fused
    Pallas kernel.  Per-(port case, src-tile) partials are int32-bounded
    (bs * N < 2^31, checked in _tiles_for and again at call time); totals
    are summed host-side in int64."""
    from .tiled import _precompute_jit

    pre = _precompute_jit(tensors)
    partials = verdict_counts_pallas(
        pre["egress"]["tmatch"],
        pre["egress"]["has_target"],
        pre["egress"]["tallow_bf"],
        pre["ingress"]["tmatch"],
        pre["ingress"]["has_target"],
        pre["ingress"]["tallow_bf"],
        n_pods=n_pods,
        interpret=_should_interpret(),
    )
    return sum_partials(partials, int(tensors["q_port"].shape[0]), n_pods)
