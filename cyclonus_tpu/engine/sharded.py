"""Mesh-sharded verdict evaluation: SPMD over the pod axis with shard_map.

Sharding layout (see SURVEY.md section 2.7 / 5):
  * every per-pod tensor (labels, ns ids, IPs) is sharded over the 1D mesh
    axis 'x'; policy tensors (selectors, targets, peers, port specs) are
    replicated — they are small.
  * each device computes verdict ROWS for its source-pod block.
  * output [N_src, N_dst, Q] stays row-sharded until fetched.

Two schedules produce bit-identical grids (docs/DESIGN.md "Multi-chip
scale-out"):

  ring (default) — the OVERLAPPED path: each device keeps only its own
      pod shard's peer-side precompute and streams peer pod-blocks
      around the mesh with jax.lax.ppermute, one hop per step, computing
      the verdict block it already holds while the next block is in
      flight (the ppermute is issued BEFORE the step's matmuls, so the
      ICI transfer hides behind the MXU work).  Per-device peer-side
      working set: O(N / n_dev) resident + one in-flight block, vs the
      all-gather schedule's O(N) replicated copy.

  allgather — the reference schedule the ring is differentially pinned
      against: the peer-side target_allows[T, N, Q] (egress) and
      tmatch[T, N] + has_target[N] (ingress) are ALL-GATHERed once per
      eval and every device contracts against the full replicated copy.

The collectives ride ICI on a real TPU slice; on CPU the same programs
run over the virtual 8-device mesh (tests/conftest.py) and in
dryrun_multichip.  Compiled programs are cached per (mesh, schedule,
shard) so repeat evaluations — and same-bucket cluster resizes — reuse
the trace (the zero-recompile elastic-resize contract).
"""

from __future__ import annotations

import inspect
import math
import os
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..telemetry import instruments as ti
from ..utils import cachekeys

try:  # JAX >= 0.4.35 exposes shard_map at top level
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore


def shard_map_no_check(fn, mesh, in_specs, out_specs):
    """shard_map with the replication check disabled, under whichever
    keyword this JAX spells it (check_vma >= 0.4.35ish, check_rep
    before)."""
    params = inspect.signature(shard_map).parameters
    check_kw = (
        {"check_vma": False}
        if "check_vma" in params
        else ({"check_rep": False} if "check_rep" in params else {})
    )
    return shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **check_kw
    )

from .kernel import (
    _bool_matmul,
    direction_precompute,
    m_tp_onehot,
    port_spec_allows,
    resolve_tier_lattice,
    selector_match,
    tier_direction_arrays,
    tier_first_match_keys,
)

# pod-axis-sharded tensor keys
_POD_KEYS = ("pod_ns_id", "pod_kv", "pod_key", "pod_ip", "pod_ip_valid")


def pod_sharded_in_specs(tensors: Dict) -> Dict:
    """shard_map in_specs for an engine tensor dict: per-pod arrays (and
    host-evaluated ip-match rows) sharded over mesh axis 'x', policy
    tensors replicated.  Shared by every pod-axis-sharded program
    (full-grid sharded, ring counts) so a new tensor key cannot end up
    sharded in one and replicated in the other."""
    in_specs: Dict = {}
    for k, v in tensors.items():
        if k in _POD_KEYS:
            in_specs[k] = (
                P("x") if np.ndim(v) == 1 else P("x", *([None] * (np.ndim(v) - 1)))
            )
        elif k == "tiers":
            # tier slabs are rule-axis arrays: replicated, leaf by leaf
            in_specs[k] = jax.tree_util.tree_map(lambda _: P(), v)
        elif k in ("ingress", "egress"):
            sub = {}
            for kk, vv in v.items():
                if kk == "host_ip_match":
                    sub[kk] = P(None, "x")
                elif kk == "port_spec":
                    sub[kk] = {k3: P() for k3 in vv}
                else:
                    sub[kk] = P()
            in_specs[k] = sub
        else:
            in_specs[k] = P()
    return in_specs


def mesh_device_context(mesh: Mesh):
    """Context manager for dispatching onto `mesh`.  A CPU mesh (the
    virtual multi-device fallback on a single-chip TPU host — see
    default_mesh) pins every dispatch in the scope to CPU so no unsharded
    op lands on the default device: a CPU-mesh evaluation must never
    touch — or require a working — TPU.  Decided from the mesh platform
    alone (querying the default backend would initialize it, which can
    hang on a dead tunnel); when CPU already IS the default backend the
    pin is a no-op."""
    import contextlib

    dev = mesh.devices.flat[0]
    if dev.platform == "cpu":
        return jax.default_device(dev)
    return contextlib.nullcontext()


def default_mesh() -> Mesh:
    """All devices of the default backend; when that's a single chip (e.g. a
    tunneled TPU) but the CPU backend exposes a virtual multi-device mesh
    (xla_force_host_platform_device_count), prefer the latter so the
    collective paths actually run multi-device."""
    devices = jax.devices()
    if len(devices) == 1:
        try:
            cpu_devices = jax.devices("cpu")
        except RuntimeError:
            cpu_devices = devices
        if len(cpu_devices) > 1:
            devices = cpu_devices
    return Mesh(np.array(devices), ("x",))


def _pad_pod_arrays(tensors: Dict, n_pods: int, n_dev: int) -> Tuple[Dict, int]:
    """Pad the pod axis to a multiple of the device count with inert rows
    (ns id -1, labels -1, invalid ip): they match no target and no peer.
    The arrays may already be LONGER than n_pods (shape bucketing pads
    them with the same inert rows at build time) — the current length,
    not n_pods, is what gets rounded up."""
    cur = int(tensors["pod_ns_id"].shape[0])
    padded = math.ceil(max(cur, n_pods, 1) / n_dev) * n_dev
    if padded == cur:
        return tensors, cur
    pad = padded - cur
    t = dict(tensors)
    t["pod_ns_id"] = np.concatenate(
        [tensors["pod_ns_id"], np.full((pad,), -1, np.int32)]
    )
    t["pod_kv"] = np.concatenate(
        [tensors["pod_kv"], np.full((pad, tensors["pod_kv"].shape[1]), -1, np.int32)]
    )
    t["pod_key"] = np.concatenate(
        [tensors["pod_key"], np.full((pad, tensors["pod_key"].shape[1]), -1, np.int32)]
    )
    t["pod_ip"] = np.concatenate(
        [tensors["pod_ip"], np.zeros((pad,), np.uint32)]
    )  # shape: (N,) uint32; sentinel: 0=invalid; mask: pod_ip_valid
    t["pod_ip_valid"] = np.concatenate(
        [tensors["pod_ip_valid"], np.zeros((pad,), bool)]
    )  # shape: (N,) bool
    for direction in ("ingress", "egress"):
        d = t[direction]
        if "host_ip_match" in d:
            d = dict(d)
            d["host_ip_match"] = np.concatenate(
                [
                    d["host_ip_match"],
                    np.zeros((d["host_ip_match"].shape[0], pad), bool),
                ],
                axis=1,
            )
            t[direction] = d
    return t, padded


def _sharded_eval(tensors: Dict) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """The per-device ALL-GATHER reference program (schedule="allgather").
    Local pod block = this device's source rows (and, symmetrically, its
    slice of every per-pod precompute); the peer side is gathered whole.
    Kept as the differential twin the overlapped ring schedule is pinned
    bit-identical against."""
    selpod = selector_match(
        tensors["sel_req_kv"],
        tensors["sel_exp_op"],
        tensors["sel_exp_key"],
        tensors["sel_exp_vals"],
        tensors["pod_kv"],
        tensors["pod_key"],
    )  # [S, Nb]
    selns = selector_match(
        tensors["sel_req_kv"],
        tensors["sel_exp_op"],
        tensors["sel_exp_key"],
        tensors["sel_exp_vals"],
        tensors["ns_kv"],
        tensors["ns_key"],
    )  # [S, M] replicated

    pre = {}
    pport = {}
    for direction in ("ingress", "egress"):
        enc = tensors[direction]
        p = direction_precompute(
            enc,
            selpod,
            selns,
            tensors["pod_ns_id"],
            tensors["pod_ip"],
            tensors["pod_ip_valid"],
        )
        if "host_ip_match" in enc:
            p["peer_match"] = jnp.where(
                enc["host_ip_mask"][:, None], enc["host_ip_match"], p["peer_match"]
            )
        pre[direction] = p
        pport[direction] = port_spec_allows(
            enc["port_spec"],
            tensors["q_port"],
            tensors["q_name"],
            tensors["q_proto"],
        )

    q = tensors["q_port"].shape[0]

    # precedence-tier precompute over the LOCAL pod block; the remote
    # side of each direction is all-gathered below exactly like the
    # NetworkPolicy arrays (docs/DESIGN.md "Precedence tiers")
    tier = None
    if "tiers" in tensors:
        tier = {
            d: tier_direction_arrays(
                tensors["tiers"][d],
                selpod,
                selns,
                tensors["pod_ns_id"],
                tensors["q_port"],
                tensors["q_name"],
                tensors["q_proto"],
            )
            for d in ("ingress", "egress")
        }

    # --- egress: local source block is the target side ---
    enc_e, pre_e = tensors["egress"], pre["egress"]
    n_b = pre_e["peer_match"].shape[1]
    peer_allow_e = (
        pre_e["peer_match"][:, :, None] & pport["egress"][:, None, :]
    ).reshape(pre_e["peer_match"].shape[0], n_b * q)
    tallow_e_local = _bool_matmul(m_tp_onehot(enc_e), peer_allow_e)  # [T, Nb*Q]
    t_e = tallow_e_local.shape[0]
    # one collective per eval: gather destination-side target_allows
    g_tallow_e = jax.lax.all_gather(
        tallow_e_local.reshape(t_e, n_b, q), "x", axis=1, tiled=True
    )  # [T, N, Q]
    n_total = g_tallow_e.shape[1]
    any_allow_e = _bool_matmul(
        pre_e["tmatch"].T, g_tallow_e.reshape(t_e, n_total * q)
    ).reshape(n_b, n_total, q)
    egress = (~pre_e["has_target"][:, None, None]) | any_allow_e  # [Sb, N, Q]
    if tier is not None:
        te = tier["egress"]
        # subject = local source block; peer side gathers like tallow
        g_peerq_e = jax.lax.all_gather(
            te["peerq"], "x", axis=1, tiled=True
        )  # [G, N, Q]
        anp_e, banp_e = tier_first_match_keys(
            te["subj"], g_peerq_e, te["anp_key"], te["banp_key"]
        )  # [Sb, N, Q]
        egress = resolve_tier_lattice(
            egress, pre_e["has_target"][:, None, None], anp_e, banp_e
        )

    # --- ingress: local source block is the peer side ---
    enc_i, pre_i = tensors["ingress"], pre["ingress"]
    peer_allow_i = (
        pre_i["peer_match"][:, :, None] & pport["ingress"][:, None, :]
    ).reshape(pre_i["peer_match"].shape[0], n_b * q)
    tallow_i_local = _bool_matmul(m_tp_onehot(enc_i), peer_allow_i)  # [T, Nb*Q]
    t_i = tallow_i_local.shape[0]
    # port-independent collectives: gather target-side matches
    g_tmatch_i = jax.lax.all_gather(pre_i["tmatch"], "x", axis=1, tiled=True)  # [T, N]
    g_has_t_i = jax.lax.all_gather(pre_i["has_target"], "x", axis=0, tiled=True)  # [N]
    any_allow_i = _bool_matmul(
        g_tmatch_i.T, tallow_i_local
    )  # [N, Sb*Q]
    ingress_t = (
        (~g_has_t_i[:, None, None]) | any_allow_i.reshape(n_total, n_b, q)
    )  # [N_dst, Sb, Q]
    if tier is not None:
        ti_ = tier["ingress"]
        # target side gathers (like tmatch); peer = local source block
        g_subj_i = jax.lax.all_gather(
            ti_["subj"], "x", axis=1, tiled=True
        )  # [G, N]
        anp_i, banp_i = tier_first_match_keys(
            g_subj_i, ti_["peerq"], ti_["anp_key"], ti_["banp_key"]
        )  # [N_dst, Sb, Q]
        ingress_t = resolve_tier_lattice(
            ingress_t, g_has_t_i[:, None, None], anp_i, banp_i
        )
    ingress_rows = jnp.swapaxes(ingress_t, 0, 1)  # [Sb, N_dst, Q]

    combined = egress & ingress_rows
    return ingress_rows, egress, combined


def _ring_grid_eval(tensors: Dict, n_dev: int, shard: int, pack: bool = False):
    """The per-device OVERLAPPED ring program: local peer-side bundle
    only, one ppermute hop per step, verdict blocks written column-wise.

    Reuses the tiled path's precompute/split/verdict bodies
    (tiled._precompute / _split_pre / _tile_verdicts_split) so the ring
    step's semantics — including the precedence-tier epilogue, whose
    min-key resolution runs INSIDE each ring step against the rotated
    subject/peer blocks — can never diverge from the single-device and
    ring-counts paths.  With `pack` the rotating bundle carries the
    32-per-word packed match slabs (tiled._split_pre), so each ppermute
    hop moves ~16x fewer peer bytes; the allgather schedule stays the
    dense reference twin the ring is pinned bit-identical against."""
    from .tiled import (
        _dst_bundle_keys,
        _precompute,
        _ring_sweep,
        _split_pre,
        _tile_verdicts_split,
    )

    pre = _precompute(tensors, pack)
    src, dst0 = _split_pre(pre)
    dev = jax.lax.axis_index("x")
    n_total = n_dev * shard
    q = tensors["q_port"].shape[0]
    init = tuple(
        jnp.zeros((shard, n_total, q), dtype=bool) for _ in range(3)
    )

    def body(step, ring, grids):
        ing, eg, comb = grids
        dst = {k: ring[k] for k in _dst_bundle_keys(ring)}
        i_blk, e_blk, c_blk = _tile_verdicts_split(src, dst, 0, shard)
        # after `step` hops we hold the bundle that originated at device
        # (dev - step) mod n_dev: its verdicts land in those columns
        col0 = ((dev - step) % n_dev) * shard
        ing = jax.lax.dynamic_update_slice(ing, i_blk, (0, col0, 0))
        eg = jax.lax.dynamic_update_slice(eg, e_blk, (0, col0, 0))
        comb = jax.lax.dynamic_update_slice(comb, c_blk, (0, col0, 0))
        return (ing, eg, comb)

    (ing, eg, comb), _ = _ring_sweep(n_dev, dst0, init, body)
    return ing, eg, comb


def mesh_schedule(schedule: Optional[str] = None) -> str:
    """Resolve the mesh exchange schedule: explicit arg, else
    CYCLONUS_MESH_SCHEDULE, else "ring" (the overlapped default;
    "allgather" keeps the replicated reference schedule)."""
    s = (schedule or os.environ.get("CYCLONUS_MESH_SCHEDULE", "ring")).lower()
    if s not in ("ring", "allgather"):
        raise ValueError(
            f"unknown mesh schedule {s!r} (want 'ring' or 'allgather')"
        )
    return s


def peer_buffer_bytes(
    tensors: Dict, n_dev: int, schedule: str, pack: bool = False
) -> int:
    """Host-side estimate of the PER-DEVICE peer-side working set of one
    sharded grid eval — the number the HBM watermark gauge records and
    the scale-out acceptance asserts on (ring < allgather at 8 devices).

    allgather: the gathered bool arrays every device holds replicated —
    egress tallow [T_e, N, Q] + ingress tmatch [T_i, N] + has [N]
    (+ the gathered tier scope blocks).  ring: TWO copies (resident +
    in-flight ppermute target) of the rotating bundle over one shard —
    tallow_bf is bf16 (2 bytes), the rest bool; with `pack` the
    tallow/tmatch legs ship as 32-per-word int32 packed slabs
    (encoding.packed_words(T) words of 4 bytes each)."""
    from .encoding import packed_words

    n = int(tensors["pod_ns_id"].shape[0])
    q = int(tensors["q_port"].shape[0])
    t_e = int(tensors["egress"]["target_ns"].shape[0])
    t_i = int(tensors["ingress"]["target_ns"].shape[0])
    g_e = g_i = 0
    if "tiers" in tensors:
        g_e = int(tensors["tiers"]["egress"]["action"].shape[0])
        g_i = int(tensors["tiers"]["ingress"]["action"].shape[0])
    if schedule == "allgather":
        return t_e * n * q + t_i * n + n + g_e * n * q + g_i * n
    shard = n // max(n_dev, 1)
    if pack:
        bundle = (
            4 * packed_words(t_e) * shard * q  # tallow_pk: int32 words
            + 4 * packed_words(t_i) * shard  # tmatch_pk
            + shard  # has_i
            + g_e * shard * q
            + g_i * shard
        )
    else:
        bundle = (
            2 * t_e * shard * q  # tallow_bf: bf16
            + t_i * shard
            + shard  # has_i
            + g_e * shard * q
            + g_i * shard
        )
    return 2 * bundle


#: compiled sharded-grid programs, keyed by (mesh devices, schedule,
#: shard, in_specs structure).  One entry per (mesh, schedule, shape
#: family) — re-jitting per eval cost a full retrace every call, and a
#: same-bucket cluster resize must hit this cache (zero-recompile
#: contract, pinned by tests/test_engine_sharded.py)
_SHARDED_PROGRAMS: Dict = {}  # cache-key: mesh, schedule, shard, pack, specs
_SHARDED_PROGRAMS_MAX = 64


def _sharded_program(
    mesh: Mesh, schedule: str, shard: int, in_specs: Dict, pack: bool = False
):
    n_dev = int(mesh.devices.size)
    leaves, treedef = jax.tree_util.tree_flatten(in_specs)
    key = (
        tuple(mesh.devices.flat),
        tuple(mesh.axis_names),
        schedule,
        shard,
        pack,
        treedef,
        tuple(leaves),
    )
    fn = _SHARDED_PROGRAMS.get(key)
    if fn is None:
        out_specs = (
            P("x", None, None),
            P("x", None, None),
            P("x", None, None),
        )
        if schedule == "ring":
            def body(t, _n_dev=n_dev, _shard=shard, _pack=pack):
                return _ring_grid_eval(t, _n_dev, _shard, _pack)
        else:
            body = _sharded_eval
        fn = jax.jit(
            shard_map_no_check(
                body, mesh=mesh, in_specs=(in_specs,), out_specs=out_specs
            )
        )
        # the persistent AOT executable cache covers the cached sharded
        # programs too (engine/aot_cache.py): a restarted process
        # adopts the ring/allgather executables for its mesh without a
        # retrace.  The partition-spec structure and the shard/pack
        # statics are program identity the arg shapes can't see, so
        # they ride in the plan.
        from . import aot_cache

        spec_digest = aot_cache.digest(
            (str(treedef), [str(x) for x in leaves])
        )
        fn = aot_cache.AotProgram(
            "sharded.grid",
            fn,
            schedule=schedule,
            plan=(
                f"shard={shard};pack={pack};"
                f"mesh={','.join(mesh.axis_names)}x{n_dev};{spec_digest}"
            ),
        )
        if cachekeys.ACTIVE:
            cachekeys.register(
                "sharded.programs",
                kind="program",
                components=cachekeys.program(
                    "mesh", "schedule", "shard", "pack", "specs"
                ),
            )
        if len(_SHARDED_PROGRAMS) >= _SHARDED_PROGRAMS_MAX:
            _SHARDED_PROGRAMS.clear()  # crude bound; programs re-jit
        _SHARDED_PROGRAMS[key] = fn
    return fn


def evaluate_class_grid_sharded(
    tensors: Dict,
    n_classes: int,
    class_of: np.ndarray,
    mesh: Optional[Mesh] = None,
    schedule: Optional[str] = None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Mesh-sharded evaluation over the COMPRESSED class grid + the
    int32 gather epilogue back to pod axes.

    `tensors` carries class-representative rows on the pod axis
    (encoding.gather_class_pod_rows); the shard_map program is exactly
    evaluate_grid_sharded over that axis — with the ring schedule this
    is the C x C ring over class representatives — and the broadcast
    back to the full pod x pod grid is two chained jnp.take gathers per
    verdict tensor — device-resident, lazy, identical in layout to the
    dense path's outputs."""
    ingress, egress, combined = evaluate_grid_sharded(
        tensors, n_classes, mesh=mesh, schedule=schedule
    )

    def g(a):
        # a: [C, C, Q] (either orientation) -> [N, N, Q]
        return jnp.take(jnp.take(a, class_of, axis=0), class_of, axis=1)

    return g(ingress), g(egress), g(combined)


def evaluate_grid_sharded(
    tensors: Dict,
    n_pods: int,
    mesh: Optional[Mesh] = None,
    schedule: Optional[str] = None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Returns (ingress[N_dst, N_src, Q], egress[N_src, N_dst, Q],
    combined[N_src, N_dst, Q]) as DEVICE-RESIDENT (immutable) jax arrays,
    pad rows stripped lazily.  `schedule` picks the peer exchange:
    "ring" (overlapped, default) or "allgather" (replicated reference);
    both are bit-identical by construction and pinned so by
    tests/test_engine_sharded.py."""
    from .encoding import pack_enabled

    mesh = mesh or default_mesh()
    schedule = mesh_schedule(schedule)
    pack = pack_enabled()
    n_dev = mesh.devices.size
    tensors, padded_n = _pad_pod_arrays(tensors, n_pods, n_dev)
    shard = padded_n // n_dev

    in_specs = pod_sharded_in_specs(tensors)
    fn = _sharded_program(mesh, schedule, shard, in_specs, pack=pack)
    ti.MESH_PEER_BYTES.set(
        peer_buffer_bytes(tensors, n_dev, schedule, pack=pack),
        schedule=schedule,
    )
    with ti.eval_flight(
        "grid.sharded", n_pods, int(tensors["q_port"].shape[0]),
        devices=int(n_dev), schedule=schedule, dispatch_only=True,
    ):
        with mesh_device_context(mesh):
            ingress_rows, egress, combined = fn(tensors)
            # stay on device: strip pad rows and fix the ingress layout
            # ([src, dst, q] -> [dst, src, q]) with lazy jnp ops
            ingress_rows = ingress_rows[:n_pods, :n_pods]
            egress = egress[:n_pods, :n_pods]
            combined = combined[:n_pods, :n_pods]
            ingress = jnp.swapaxes(ingress_rows, 0, 1)
    return ingress, egress, combined
