"""Persistent AOT executable cache (docs/DESIGN.md "Cold start & chaos").

The JAX persistent compilation cache (engine/__init__.py) already skips
the XLA *compile* on a warm restart, but a fresh process still pays the
full Python *trace* of every program plus the cache's own lookup
machinery — at the bench shape that trace+lookup residue is seconds of
the 7.2s warmup, and it recurs for every compiled program family.  This
module goes the rest of the way: compiled executables are SERIALIZED
(jax.experimental.serialize_executable — the loaded binary, not the
StableHLO) keyed by

    (program name, arg shape/dtype signature = the shape bucket,
     mesh signature, schedule, dtype plan / pack)

so a restarted process ADOPTS the executable with zero traces and zero
compiles — the AOT_COMPILES counter stays flat, which is exactly what
tests/test_aot_cache.py's subprocess restart gate asserts.

Robustness contract (the engine/autotune.py discipline): the cache is
advisory.  A corrupt, truncated, version-skewed, wrong-key, or
concurrently-replaced entry degrades to a fresh trace+compile — load
NEVER raises — and a failed write is a logged warning.  Every entry is
its own file written atomically (tmp + os.replace), so concurrent
processes warming different programs can never clobber each other and a
reader can never observe a half-written entry; same-key racers both
wrote a valid executable and the last one wins.  Entries embed the full
key plus CACHE_VERSION and the jax/backend stamp: a jaxlib upgrade or a
different device kind silently invalidates instead of loading an
executable the runtime cannot run.

Security note: entries are pickles (the serialize_executable payload
format), loaded only from the user's own cache directory — the same
trust boundary as the autotune cache and JAX's own compilation cache.

CYCLONUS_AOT_CACHE: cache directory; "0"/"" disables entirely (the test
suite default — tests/conftest.py — so suites never share executables
through the developer's home); unset -> the per-user default below.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import tempfile
from typing import Any, Dict, Optional, Tuple

from ..utils import cachekeys

log = logging.getLogger(__name__)

#: bump when the entry layout changes: stale versions are ignored
#: (fresh compile), never migrated
CACHE_VERSION = 1

_DEFAULT_DIR = os.path.join("~", ".cache", "cyclonus_tpu", "aot")


def cache_dir() -> Optional[str]:  # never-raises
    """Resolved cache directory, or None when persistence is disabled."""
    raw = os.environ.get("CYCLONUS_AOT_CACHE")
    if raw is None:
        raw = _DEFAULT_DIR
    raw = raw.strip()
    if raw in ("", "0"):
        return None
    return os.path.expanduser(raw)


def platform_stamp() -> str:
    """The (jax + jaxlib version, backend, device kind, device count)
    stamp an entry must match to load: a serialized executable is a
    binary for one runtime on one device topology — skew means
    recompile, never a load attempt that the runtime rejects (or worse,
    misruns).  jaxlib rides the stamp SEPARATELY from jax: the payload
    bytes are jaxlib's, and the two versions can be pinned
    independently — a jaxlib-only upgrade used to slip past the key
    (found by the tools/cachelint.py key-surface audit; pinned by
    tests/test_aot_cache.py)."""
    import jax

    try:
        import jaxlib

        jaxlib_v = getattr(jaxlib, "__version__", "?")
    except Exception:  # no separate jaxlib dist: jax's version rules
        jaxlib_v = "?"
    devs = jax.devices()
    return (
        f"jax={jax.__version__};jaxlib={jaxlib_v};"
        f"backend={jax.default_backend()};"
        f"kind={devs[0].device_kind};n={len(devs)}"
    )


def make_key(
    name: str,
    signature: str,
    *,
    schedule: str = "single",
    plan: str = "",
) -> str:
    """Stable string key for one executable: the program NAME, the arg
    shape/dtype SIGNATURE (the shape bucket — bucketing is what makes
    two processes lower byte-identical programs), the mesh/platform
    stamp, the exchange SCHEDULE (single / ring / allgather), and the
    dtype PLAN (packed32 / int8 / bf16 + any per-engine extras)."""
    return json.dumps(
        {
            "name": name,
            "sig": signature,
            "platform": platform_stamp(),
            "schedule": schedule,
            "plan": plan,
        },
        sort_keys=True,
        separators=(",", ":"),
    )


def _entry_path(base: str, key: str) -> str:  # never-raises
    d = hashlib.sha256(key.encode("utf-8")).hexdigest()[:32]
    return os.path.join(base, f"{d}.aotx")


def digest(obj) -> str:  # never-raises
    """Stable short digest of `repr(obj)` — THE helper for folding
    program identity the arg shapes can't see (unpack leaf metas,
    partition-spec structures) into a cache key's plan.  One
    implementation on purpose: the digest width/encoding is part of
    the key, so changing it is a cache-invalidation event that must
    happen in exactly one place."""
    return hashlib.sha256(repr(obj).encode("utf-8")).hexdigest()[:16]


def load(key: str):  # never-raises
    """The deserialized, loaded executable for `key`, or None (disabled
    / missing / corrupt / version-skewed / key-collided / any
    deserialization failure).  Never raises."""
    base = cache_dir()
    if base is None:
        return None
    path = _entry_path(base, key)
    try:
        with open(path, "rb") as f:
            entry = pickle.load(f)
    except FileNotFoundError:
        return None
    except Exception:
        # truncated pickle, chmod surprise, poisoned bytes: all degrade
        # to a fresh compile (the chaos harness injects exactly this)
        _count("corrupt")
        return None
    try:
        if (
            not isinstance(entry, dict)
            or entry.get("v") != CACHE_VERSION
            or entry.get("key") != key  # digest collision or stale stamp
        ):
            _count("stale")
            return None
        from jax.experimental import serialize_executable as se

        return se.deserialize_and_load(
            entry["payload"], entry["in_tree"], entry["out_tree"]
        )
    except Exception as e:
        # e.g. jaxlib CPU "Symbols not found" for some fusion patterns
        # when an executable crosses processes: degrade to a fresh
        # compile.  Truncated message — the full symbol list is noise.
        _count("corrupt")
        log.info(
            "aot cache entry unloadable (%s): %s", path, str(e)[:160]
        )
        return None


def store(key: str, compiled) -> bool:  # never-raises
    """Serialize `compiled` under `key` (atomic tmp + os.replace).
    Returns True when written; any failure — an executable kind the
    backend cannot serialize (pallas custom calls on some runtimes),
    a full disk — logs and returns False, never raising into the
    evaluation that just compiled a perfectly good program."""
    base = cache_dir()
    if base is None:
        return False
    try:
        from jax.experimental import serialize_executable as se

        payload, in_tree, out_tree = se.serialize(compiled)
        entry = {
            "v": CACHE_VERSION,
            "key": key,
            "payload": payload,
            "in_tree": in_tree,
            "out_tree": out_tree,
        }
        os.makedirs(base, exist_ok=True)
        path = _entry_path(base, key)
        fd, tmp = tempfile.mkstemp(dir=base, prefix=".aot-")
        try:
            with os.fdopen(fd, "wb") as f:
                pickle.dump(entry, f)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        _count("store")
        return True
    except Exception as e:
        _count("unserializable")
        log.info("aot cache store failed for %s: %s", key[:120], e)
        return False


def _count(outcome: str) -> None:
    from ..telemetry import instruments as ti

    ti.AOT_CACHE.inc(outcome=outcome)


def counters() -> Dict[str, Any]:
    """The per-process AOT cache forensics bench.py records as
    detail.cold_start.aot_cache: hits (executables adopted from disk —
    `adopted` aliases it for the acceptance schema), misses, stores,
    and fresh compiles actually paid (the restart gate's flat line)."""
    from ..telemetry import instruments as ti

    return {
        "hits": int(ti.AOT_CACHE.value(outcome="hit")),
        "misses": int(ti.AOT_CACHE.value(outcome="miss")),
        "adopted": int(ti.AOT_CACHE.value(outcome="hit")),
        "stores": int(ti.AOT_CACHE.value(outcome="store")),
        "corrupt": int(ti.AOT_CACHE.value(outcome="corrupt")),
        "compiles": int(ti.AOT_COMPILES.value()),
        "dir": cache_dir(),
    }


def _leaf_sig(leaf) -> Tuple:
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        return ("a", tuple(int(d) for d in shape), str(dtype))
    # non-array leaf (None never reaches here — it is a pytree node):
    # a python scalar lowers as a weak-typed literal, so its TYPE is
    # part of the program identity but its value is not
    return ("p", type(leaf).__name__)


def call_key(args: tuple, kwargs: dict):
    """Hashable shape/dtype key of a call's argument pytree — the
    per-dispatch fast path (a treedef + leaf-sig tuple; no string
    building on the hot path).  `signature_string` renders it for the
    persisted key only when a call actually needs resolving."""
    from jax import tree_util as jtu

    leaves, treedef = jtu.tree_flatten((args, kwargs))
    return (treedef, tuple(_leaf_sig(x) for x in leaves))


def signature_string(key) -> str:
    """The stable string form of a call_key — the shape-bucket half of
    the persisted cache key."""
    treedef, leaf_sigs = key
    return json.dumps(
        [str(treedef)] + [list(s) for s in leaf_sigs],
        separators=(",", ":"),
    )


class AotProgram:
    """Wrap a jitted callable with the persistent executable cache.

    On the first call per argument signature: try to ADOPT a serialized
    executable (zero trace, zero compile); otherwise lower+compile via
    the wrapped jit (counted in AOT_COMPILES) and persist the result.
    Later calls with the same signature dispatch the resolved
    executable directly.  Any failure anywhere — an unserializable
    program, a runtime that rejects the AOT path, statics the lowering
    chokes on — pins a per-signature FALLBACK to the plain jitted
    callable, so the wrapper can never be less robust than the jit it
    wraps.

    Not thread-safe by design: engines issue evaluations from one
    thread at a time (api.py threading model), and the abandoned-
    autotune orphan only ever calls through programs resolved earlier
    on the issuing thread (dict reads are atomic under the GIL; the
    worst interleaving resolves the same signature twice, both valid).
    """

    def __init__(
        self,
        name: str,
        jitted,
        *,
        plan: str = "",
        schedule: str = "single",
        static_argnames: Tuple[str, ...] = (),
    ):
        self._name = name
        self._jitted = jitted
        self._plan = plan
        self._schedule = schedule
        self._static_argnames = tuple(static_argnames)
        if cachekeys.ACTIVE:
            # the key-mutation harness (tests/keyharness.py) proves
            # each component miss-on-mutate; the fingerprint is the
            # persisted key with the per-call signature left symbolic
            cachekeys.register(
                f"aot:{name}",
                kind="persisted",
                components=cachekeys.program(
                    "name", "signature", "platform", "schedule", "plan"
                ),
                fingerprint=make_key(
                    name, "<signature>", schedule=schedule, plan=plan
                ),
            )
        # (call_key, statics) -> compiled | None(=fallback); keyed by
        # the hashable tuple so steady-state dispatches never build a
        # signature string
        self._programs: Dict[Any, Any] = {}

    def _cache_size(self) -> int:
        """Trace-cache size of the wrapped jit — the zero-recompile
        elastic-resize gates read this through the program caches.
        Adopted executables never trace, so they never count."""
        return self._jitted._cache_size()

    def __call__(self, *args, **kwargs):
        if cache_dir() is None:
            return self._jitted(*args, **kwargs)
        statics = tuple(
            (k, kwargs[k]) for k in self._static_argnames if k in kwargs
        )
        dyn_kwargs = {
            k: v for k, v in kwargs.items() if k not in self._static_argnames
        }
        key = (call_key(args, dyn_kwargs), statics)
        if key not in self._programs:
            sig = signature_string(key[0]) + "|" + repr(statics)
            self._programs[key] = self._resolve(sig, args, kwargs)
        compiled = self._programs[key]
        if compiled is None:
            return self._jitted(*args, **kwargs)
        try:
            return compiled(*args, **dyn_kwargs)
        except Exception:
            # a loaded executable the runtime rejects at CALL time
            # (device moved, donation mismatch): fall back for good
            _count("call_fallback")
            self._programs[key] = None
            return self._jitted(*args, **kwargs)

    def _resolve(self, sig: str, args, kwargs):
        from ..telemetry import instruments as ti

        key = make_key(
            self._name, sig, schedule=self._schedule, plan=self._plan
        )
        try:
            compiled = load(key)
        except Exception:  # belt and braces: load already never raises
            compiled = None
        if compiled is not None:
            ti.AOT_CACHE.inc(outcome="hit")
            return compiled
        ti.AOT_CACHE.inc(outcome="miss")
        try:
            compiled = self._jitted.lower(*args, **kwargs).compile()
            ti.AOT_COMPILES.inc()
        except Exception as e:
            # lowering surprises (unsupported statics, tracer leaks in
            # exotic paths) must not break evaluation: plain jit from
            # here on for this signature
            log.info("aot lower/compile fallback for %s: %s", self._name, e)
            ti.AOT_CACHE.inc(outcome="fallback")
            return None
        store(key, compiled)
        return compiled
