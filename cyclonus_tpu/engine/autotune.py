"""Persisted counts-kernel autotune cache (docs/DESIGN.md "Bit-packed
kernel").

The engine's on-device autotune (api._autotune_slab / _autotune_packed)
times candidate kernels from the pinned precompute and keeps the winner
for the engine's life.  That search costs real wall-clock on every fresh
process — candidate compiles plus min-of-N timed rounds — for an answer
that is a pure function of (shape bucket, mesh, dtype plan).  This
module persists the winner to disk under exactly that key, so a
restarted process ADOPTS the tuned configuration with zero candidate
search (asserted via the AUTOTUNE_SEARCHES counter in
tests/test_engine_packed.py).

Robustness contract: the cache is advisory.  A corrupt, truncated,
version-skewed, or otherwise surprising file degrades to a fresh search
— load_winner never raises (the tunnel_wait truncated-JSON discipline)
— and a failed write is a logged warning, never an error.  Writes are
atomic (tmp + os.replace) and read-merge-write so concurrent processes
tuning different buckets don't clobber each other (last writer wins per
key, which is fine: both wrote a measured winner).

CYCLONUS_AUTOTUNE_CACHE: cache file path; "0"/"" disables persistence
entirely (the test suite default — tests/conftest.py — so suites never
share state through the user's home); unset -> the per-user default
below.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
from typing import Any, Dict, Optional

from ..utils import cachekeys

log = logging.getLogger(__name__)

#: bump when the entry layout or the meaning of a winner changes: stale
#: versions are ignored (fresh search), never migrated
CACHE_VERSION = 1

#: winner kernels a persisted entry may name; anything else is treated
#: as corrupt (a newer writer's kernel kinds must not crash an older
#: reader — it re-searches instead)
KNOWN_KERNELS = ("default", "slab", "packed")

_DEFAULT_PATH = os.path.join(
    "~", ".cache", "cyclonus_tpu", "autotune.json"
)


def cache_path() -> Optional[str]:  # never-raises
    """Resolved cache file path, or None when persistence is disabled."""
    raw = os.environ.get("CYCLONUS_AUTOTUNE_CACHE")
    if raw is None:
        raw = _DEFAULT_PATH
    raw = raw.strip()
    if raw in ("", "0"):
        return None
    return os.path.expanduser(raw)


def make_key(
    shape_bucket: Dict[str, Any], mesh: str, dtype_plan: str
) -> str:
    """Stable string key for one tuned configuration: the SHAPE BUCKET
    (the bucketed dims that select compiled programs — pod axis, target
    axes, case count, tiered/compressed flags), the MESH signature
    (backend + device kind + count), and the DTYPE PLAN (packed32 /
    int8 / bf16).  Two processes with equal keys run byte-identical
    candidate programs, which is what makes the winner transferable."""
    key = json.dumps(
        {"shape": shape_bucket, "mesh": mesh, "dtype": dtype_plan},
        sort_keys=True,
        separators=(",", ":"),
    )
    if cachekeys.ACTIVE:
        cachekeys.register(
            "autotune",
            kind="persisted",
            components=cachekeys.program(
                "shape_bucket", "mesh", "dtype_plan"
            ),
            fingerprint=key,
        )
    return key


def _read_all(path: str) -> Dict[str, Any]:  # never-raises
    """The whole cache file as a dict — {} on ANY problem (missing,
    truncated JSON, wrong top-level type, version skew).  The handler
    is deliberately BROAD: the old (OSError, ValueError) pair let a
    pathological entry escape the documented any-problem contract
    (e.g. RecursionError from absurd nesting) — found by
    tools/cachelint.py CC005."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
    except FileNotFoundError:
        return {}
    except Exception as e:
        log.debug("autotune cache unreadable (%s): %s", path, e)
        return {}
    if not isinstance(data, dict) or data.get("v") != CACHE_VERSION:
        return {}
    entries = data.get("entries")
    return entries if isinstance(entries, dict) else {}


def load_winner(key: str) -> Optional[Dict[str, Any]]:  # never-raises
    """The persisted winner for `key`, or None (disabled / missing /
    corrupt / stale / malformed entry).  Returns the winner dict
    ({"kernel": ..., optional "bs"/"bd", ...}); timings ride along under
    "timings" for forensics but are not re-validated."""
    path = cache_path()
    if path is None:
        return None
    entry = _read_all(path).get(key)
    if not isinstance(entry, dict):
        return None
    winner = entry.get("winner")
    if not isinstance(winner, dict) or winner.get("kernel") not in KNOWN_KERNELS:
        return None
    for dim in ("bs", "bd"):
        v = winner.get(dim)
        if v is not None and not isinstance(v, int):
            return None
    return winner


def store_winner(  # never-raises
    key: str, winner: Dict[str, Any], timings: Optional[Dict[str, Any]] = None
) -> bool:
    """Persist `winner` under `key` (read-merge-atomic-replace).
    Returns True when written; failures log and return False — a broken
    cache disk must never take down the engine that just finished a
    perfectly good search.  The handler is BROAD on purpose: the old
    `except OSError` let json.dump's TypeError on a non-serializable
    winner/timing value escape into the evaluation that just finished a
    perfectly good search, violating this very docstring (REAL bug
    surfaced by tools/cachelint.py CC005; regression-pinned in
    tests/test_cachelint.py)."""
    path = cache_path()
    if path is None:
        return False
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        entries = _read_all(path)
        entries[key] = {"winner": dict(winner), "timings": dict(timings or {})}
        fd, tmp = tempfile.mkstemp(
            dir=os.path.dirname(path) or ".", prefix=".autotune-"
        )
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump({"v": CACHE_VERSION, "entries": entries}, f)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return True
    except Exception as e:
        log.warning("autotune cache write failed (%s): %s", path, e)
        return False
