"""JAX verdict kernels (single-device path; sharded.py wraps these with
shard_map over a Mesh).

The decision procedure mirrors matcher/core.py (and thus the reference's
policy.go:138-174), restructured for the MXU:

  per direction d in {ingress, egress}:
    selpod[S, N]      selector s matches pod n's labels        (int compares)
    tmatch[T, N]      target t applies to pod n                (ns eq AND sel)
    peer_match[P, N]  peer p matches pod n (ports aside)       (kind switch)
    pport[P, Q]       peer p's port spec allows port case q    (int compares)
    peer_allow[P,N,Q] = peer_match & pport
    tallow[T, N, Q]   = one_hot(peer->target) @ peer_allow     <- MXU matmul
    any_allow[n,m,Q]  = tmatch^T @ tallow                      <- MXU matmul
    allowed[n, m, q]  = NOT has_target[n] OR any_allow > 0

  combined[s, d, q] = egress_allowed[s, d, q] AND ingress_allowed[d, s, q]

All tensors are boolean/integer; matmuls run in bfloat16 with float32
accumulation, so the >0 threshold is exact (counts are small positive
integers, never rounded to zero).
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from ..utils import contracts
from .encoding import (
    EXP_DOES_NOT_EXIST,
    EXP_EXISTS,
    EXP_IN,
    EXP_NONE,
    EXP_NOT_IN,
    PACK_BITS,
    packed_words,
    NS_ALL,
    NS_EXACT,
    NS_SELECTOR,
    PEER_ALL,
    PEER_ALL_PORTS,
    PEER_IP,
    PEER_POD,
    POD_SELECTOR,
    PORT_INT,
    PORT_NAMED,
    PORT_NIL,
    TIER_ACT_ALLOW,
    TIER_ACT_NONE,
    TIER_ACT_PASS,
    TIER_ANP,
    TIER_BANP,
    TIER_KEY_NONE,
)


@contracts.args(
    sel_req_kv="(S, R) int32",
    sel_exp_op="(S, E) int32",
    sel_exp_key="(S, E) int32",
    sel_exp_vals="(S, E, V) int32",
    kv="(N, L) int32",
    key="(N, L) int32",
)
def selector_match(
    sel_req_kv: jnp.ndarray,  # [S, R]
    sel_exp_op: jnp.ndarray,  # [S, E]
    sel_exp_key: jnp.ndarray,  # [S, E]
    sel_exp_vals: jnp.ndarray,  # [S, E, V]
    kv: jnp.ndarray,  # [N, L]
    key: jnp.ndarray,  # [N, L]
) -> jnp.ndarray:
    """[S, N] bool: selector s matches label-set n.
    Mirrors kube/labels.py is_labels_match_label_selector."""
    # matchLabels: every non-pad required kv id must be present
    # present[S, N, R] = any_L(kv[n, l] == req[s, r])
    present = jnp.any(
        kv[None, :, None, :] == sel_req_kv[:, None, :, None], axis=-1
    )
    req_ok = jnp.all((sel_req_kv[:, None, :] == -1) | present, axis=-1)  # [S, N]

    # matchExpressions
    has_key = jnp.any(
        key[None, :, None, :] == sel_exp_key[:, None, :, None], axis=-1
    )  # [S, N, E]
    val_hit = jnp.any(
        (sel_exp_vals[:, None, :, :, None] != -1)
        & (kv[None, :, None, None, :] == sel_exp_vals[:, None, :, :, None]),
        axis=(-1, -2),
    )  # [S, N, E]
    op = sel_exp_op[:, None, :]  # [S, 1, E]
    exp_ok = jnp.where(
        op == EXP_NONE,
        True,
        jnp.where(
            op == EXP_IN,
            has_key & val_hit,
            jnp.where(
                op == EXP_NOT_IN,
                has_key & ~val_hit,
                jnp.where(op == EXP_EXISTS, has_key, ~has_key),
            ),
        ),
    )  # [S, N, E]
    return req_ok & jnp.all(exp_ok, axis=-1)


@contracts.args(
    selpod="(S, N) bool",
    selns="(S, M) bool",
    pod_ns_id="(N,) int32",
    pod_ip="(N,) uint32",
    pod_ip_valid="(N,) bool",
)
def direction_precompute(
    enc: Dict[str, jnp.ndarray],
    selpod: jnp.ndarray,  # [S, N] selector-vs-pod-labels
    selns: jnp.ndarray,  # [S, M] selector-vs-namespace-labels
    pod_ns_id: jnp.ndarray,  # [N]
    pod_ip: jnp.ndarray,  # [N] uint32
    pod_ip_valid: jnp.ndarray,  # [N] bool
) -> Dict[str, jnp.ndarray]:
    """Per-direction pod-resolution: tmatch[T, N], has_target[N],
    peer_match[P, N]."""
    # targets: namespace name equality + pod selector
    tmatch = (enc["target_ns"][:, None] == pod_ns_id[None, :]) & jnp.take(
        selpod, enc["target_sel"], axis=0
    )  # [T, N]
    has_target = jnp.any(tmatch, axis=0)  # [N]

    # pod-peer namespace matching
    ns_sel_match = jnp.take(
        selns, jnp.maximum(enc["peer_ns_sel"], 0), axis=0
    )  # [P, M] (garbage rows masked by kind below)
    ns_match_by_pod = jnp.take(ns_sel_match, pod_ns_id, axis=1)  # [P, N]
    ns_kind = enc["peer_ns_kind"][:, None]
    ns_ok = jnp.where(
        ns_kind == NS_EXACT,
        enc["peer_ns_id"][:, None] == pod_ns_id[None, :],
        jnp.where(ns_kind == NS_SELECTOR, ns_match_by_pod, True),
    )  # [P, N]

    # pod-peer pod matching
    pod_sel_match = jnp.take(
        selpod, jnp.maximum(enc["peer_pod_sel"], 0), axis=0
    )  # [P, N]
    pod_ok = jnp.where(
        enc["peer_pod_kind"][:, None] == POD_SELECTOR, pod_sel_match, True
    )

    # ip peers (IPv4 kernel; v6 rows are patched host-side)
    in_cidr = (
        enc["ip_is_v4"][:, None]
        & pod_ip_valid[None, :]
        & ((pod_ip[None, :] & enc["ip_mask"][:, None]) == enc["ip_base"][:, None])
    )  # [P, N]
    # pod_ip's 0-sentinel is a real address (0.0.0.0): an invalid pod
    # must never register as inside an except block, so the validity
    # mask guards this comparison too — today in_cidr already zeroes
    # those columns, but the except term must hold the contract on its
    # own (shapelint SC003 on the pod_ip/pod_ip_valid declaration)
    in_except = jnp.any(
        enc["ex_valid"][:, :, None]
        & pod_ip_valid[None, None, :]
        & (
            (pod_ip[None, None, :] & enc["ex_mask"][:, :, None])
            == enc["ex_base"][:, :, None]
        ),
        axis=1,
    )  # [P, N]
    ip_ok = in_cidr & ~in_except

    kind = enc["peer_kind"][:, None]
    peer_match = jnp.where(
        (kind == PEER_ALL) | (kind == PEER_ALL_PORTS),
        True,
        jnp.where(kind == PEER_IP, ip_ok, ns_ok & pod_ok),
    )  # [P, N]

    return {"tmatch": tmatch, "has_target": has_target, "peer_match": peer_match}


@contracts.args(
    q_port="(Q,) int32", q_name="(Q,) int32", q_proto="(Q,) int32"
)
def port_spec_allows(
    spec: Dict[str, jnp.ndarray],
    q_port: jnp.ndarray,  # [Q] int32
    q_name: jnp.ndarray,  # [Q] int32 (-1: unnamed)
    q_proto: jnp.ndarray,  # [Q] int32
) -> jnp.ndarray:
    """[P, Q] bool: peer p's port matcher allows port case q.
    Mirrors matcher/core.py SpecificPortMatcher.allows / AllPortMatcher."""
    kind = spec["item_kind"][:, :, None]  # [P, I, 1]
    proto_ok = spec["item_proto"][:, :, None] == q_proto[None, None, :]
    item_ok = jnp.where(
        kind == PORT_NIL,
        proto_ok,
        jnp.where(
            kind == PORT_INT,
            (spec["item_port"][:, :, None] == q_port[None, None, :]) & proto_ok,
            jnp.where(
                kind == PORT_NAMED,
                (spec["item_name"][:, :, None] == q_name[None, None, :]) & proto_ok,
                False,  # pad
            ),
        ),
    )  # [P, I, Q]
    rng_ok = (
        (spec["rng_from"][:, :, None] <= q_port[None, None, :])
        & (q_port[None, None, :] <= spec["rng_to"][:, :, None])
        & (spec["rng_proto"][:, :, None] == q_proto[None, None, :])
    )  # [P, R, Q]
    any_ok = jnp.any(item_ok, axis=1) | jnp.any(rng_ok, axis=1)
    return spec["spec_all"][:, None] | any_ok  # [P, Q]


def _bool_matmul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """(a @ b) > 0 computed on the MXU: bf16 inputs, f32 accumulation."""
    return (
        jnp.matmul(
            a.astype(jnp.bfloat16),
            b.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )
        > 0.0
    )


# --- bit-packed contraction (docs/DESIGN.md "Bit-packed kernel") ----------


def pack_bool_words_jnp(a: jnp.ndarray, axis: int = 0) -> jnp.ndarray:
    """Device twin of encoding.pack_bool_words: pack a bool array
    32-per-int32-word along `axis`.  Bit values are summed as disjoint
    shifted powers of two — exactly the bitwise OR (no carries, bit 31
    rides the int32 sign) — so the twins are bit-identical by
    construction (pinned by tests/test_engine_packed.py)."""
    a = jnp.moveaxis(a, axis, 0)
    t = a.shape[0]
    w = packed_words(t)
    total = w * PACK_BITS  # tile: 32 — the 32-per-word round-up, SC004-proved
    pad = total - t
    if pad:
        a = jnp.concatenate(
            [a, jnp.zeros((pad,) + a.shape[1:], dtype=a.dtype)], axis=0
        )
    bits = a.reshape((w, PACK_BITS) + a.shape[1:]).astype(jnp.int32)
    shifts = jax.lax.shift_left(
        jnp.int32(1), jnp.arange(PACK_BITS, dtype=jnp.int32)
    ).reshape((1, PACK_BITS) + (1,) * (a.ndim - 1))
    words = jnp.sum(bits * shifts, axis=1, dtype=jnp.int32)
    return jnp.moveaxis(words, 0, axis)


def packed_any(a_pk: jnp.ndarray, b_pk: jnp.ndarray) -> jnp.ndarray:
    """[A, B] bool: OR_w (a_pk[w, a] AND b_pk[w, b]) != 0 — the packed
    twin of `_bool_matmul(a.T, b) over a [T, A] x [T, B] contraction`,
    with the target axis pre-packed 32-per-word (a_pk [W, A], b_pk
    [W, B] int32).  A lax.scan walks the W words sequentially with one
    [A, B] int32 accumulator, so no [W, A, B] intermediate ever
    materializes; W is ceil(T/32), which is what cuts the contraction
    depth 32x vs the elementwise bool form."""

    def body(acc, wab):
        wa, wb = wab  # [A], [B]
        return acc | (wa[:, None] & wb[None, :]), None

    init = jnp.zeros((a_pk.shape[1], b_pk.shape[1]), dtype=jnp.int32)
    acc, _ = jax.lax.scan(body, init, (a_pk, b_pk))
    return acc != 0


@contracts.args(
    pod_ip="(N,) uint32",
    pod_ip_valid="(N,) bool",
    pmask="(K,) uint32",
    pbases="(K, B) uint32",
    pindex="(K, B) int32",
)
def lpm_partition_signature(
    pod_ip: jnp.ndarray,  # [N] uint32
    pod_ip_valid: jnp.ndarray,  # [N] bool
    pmask: jnp.ndarray,  # [K] uint32 partition masks (LPM order)
    pbases: jnp.ndarray,  # [K, B] uint32 sorted bases, 0xFFFFFFFF pad
    pindex: jnp.ndarray,  # [K, B] int32 global atom ids, -1 pad
) -> jnp.ndarray:
    """[K, N] int32 TSS/LPM partition signature (docs/DESIGN.md "CIDR
    tuple-space pre-classification"): the global atom index pod n's IP
    matches within partition k, or -1 (no base equals pod_ip & pmask[k],
    or the IP is invalid).  Within a partition at most one base can
    match — pod_ip & mask is one value — so the leftmost binary search
    over the sorted bases is the whole trie walk.  Bit-identical to the
    numpy twin cidrspace.CidrSpace.signature_host (pinned by
    tests/test_engine_cidr.py); pad slots are rejected by their -1
    pindex, never by the pad base value, so a real 255.255.255.255 base
    (which ties the pad and wins the leftmost search) still resolves."""
    key = pod_ip[None, :] & pmask[:, None]  # [K, N] uint32
    pos = jax.vmap(partial(jnp.searchsorted, side="left"))(pbases, key)
    pos = jnp.minimum(pos, pbases.shape[1] - 1)  # [K, N]
    hit = jnp.take_along_axis(pbases, pos, axis=1) == key
    idx = jnp.take_along_axis(pindex, pos, axis=1)
    return jnp.where(
        hit & (idx >= 0) & pod_ip_valid[None, :], idx, jnp.int32(-1)
    ).astype(jnp.int32)


def m_tp_onehot(enc: Dict) -> jnp.ndarray:
    """[T, P] bool peer->target one-hot, built ON DEVICE from the [P]
    peer_target index vector.  The dense matrix reaches ~70 MB at the
    10k-policy bench scale — shipping the index vector instead cut the
    engine's host->device transfer from ~7 s to <1 s over a tunneled
    chip (the one-hot compare is free next to the verdict matmuls)."""
    t = enc["target_ns"].shape[0]
    pt = enc["peer_target"]
    return pt[None, :] == jnp.arange(t, dtype=pt.dtype)[:, None]


def direction_allowed(
    tmatch_target: jnp.ndarray,  # [T, Nt] target-side pods
    has_target: jnp.ndarray,  # [Nt]
    m_tp: jnp.ndarray,  # [T, P] peer->target one-hot
    peer_match: jnp.ndarray,  # [P, Np] peer-side pods
    pport: jnp.ndarray,  # [P, Q]
    pack: bool = False,
) -> jnp.ndarray:
    """[Nt, Np, Q] bool: direction verdict for (target-side pod, peer-side
    pod, port case).  With pack=True the dominant target-axis contraction
    runs over 32-per-word packed bitmaps (packed_any) instead of the
    bf16 matmul — bit-identical by construction, gated differentially by
    the fuzz and packed parity suites."""
    n_p, n_np = peer_match.shape
    q = pport.shape[1]
    # peer_allow[P, Np*Q]
    peer_allow = (peer_match[:, :, None] & pport[:, None, :]).reshape(n_p, n_np * q)
    tallow = _bool_matmul(m_tp, peer_allow)  # [T, Np*Q]
    if pack:
        any_allow = packed_any(
            pack_bool_words_jnp(tmatch_target),  # [W, Nt]
            pack_bool_words_jnp(tallow),  # [W, Np*Q]
        )
    else:
        any_allow = _bool_matmul(tmatch_target.T, tallow)  # [Nt, Np*Q]
    allowed = (~has_target[:, None]) | any_allow
    return allowed.reshape(-1, n_np, q)


# --- precedence-tier resolution epilogue ----------------------------------
#
# The ANP/BANP lattice (docs/DESIGN.md "Precedence tiers") replaces the
# bool-OR assumption with FIRST-MATCH-BY-PRIORITY: tier rows carry an
# int8 action and an int32 rank (encoding.TierDirectionEncoding), and the
# first matching rule of a tier is the min over matching rows of the
# combined key rank * 4 + action (actions are 1..3, so key % 4 recovers
# the winning action and min-of-keys == first-match because ranks are the
# resolution order).  Rows of one rule share its rank, which makes the
# within-rule peer OR exact under the min.  TIER_KEY_NONE (2^30) is the
# no-match identity.  All of it composes with the class-compressed grid
# unchanged: tier rules observe pods only through (ns id, shared-table
# selector matches), both part of the class signature.


def tier_scope_match(
    ns_sel: jnp.ndarray,  # [G] selector ids (namespace labels)
    pod_kind: jnp.ndarray,  # [G] POD_ALL | POD_SELECTOR
    pod_sel: jnp.ndarray,  # [G] selector ids (pod labels; -1 when ALL)
    selpod: jnp.ndarray,  # [S, N]
    selns: jnp.ndarray,  # [S, M]
    pod_ns_id: jnp.ndarray,  # [N]
) -> jnp.ndarray:
    """[G, N] bool: tier scope g (a subject or peer) matches pod n —
    namespace labels via selns, pod labels via selpod (the shared
    selector table; mirrors tiers.model.scope_matches)."""
    ns_by_pod = jnp.take(
        jnp.take(selns, ns_sel, axis=0), pod_ns_id, axis=1
    )  # [G, N]
    pod_m = jnp.take(selpod, jnp.maximum(pod_sel, 0), axis=0)  # [G, N]
    pod_ok = jnp.where(pod_kind[:, None] == POD_SELECTOR, pod_m, True)
    return ns_by_pod & pod_ok


def tier_keys(tenc: Dict[str, jnp.ndarray]) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(anp_key [G], banp_key [G]) int32 priority keys: rank * 4 + action
    for real rows of each tier, TIER_KEY_NONE elsewhere (pad rows carry
    action 0 and are inert in both)."""
    act = tenc["action"].astype(jnp.int32)  # int8 verdict slab -> key arith
    key = tenc["rank"] * 4 + act
    tier = tenc["tier"].astype(jnp.int32)
    valid = act > TIER_ACT_NONE
    none = jnp.int32(TIER_KEY_NONE)
    anp = jnp.where(valid & (tier == TIER_ANP), key, none)
    banp = jnp.where(valid & (tier == TIER_BANP), key, none)
    return anp, banp


def tier_first_match_keys(
    subj: jnp.ndarray,  # [G, A] bool — subject side (target pods)
    peerq: jnp.ndarray,  # [G, B, Q] bool — peer side x port cases
    anp_key: jnp.ndarray,  # [G] int32
    banp_key: jnp.ndarray,  # [G] int32
    chunk: int = 8,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """([A, B, Q], [A, B, Q]) int32 min matching keys per tier.

    Scans the rule axis in `chunk`-row slices so the [c, A, B, Q] match
    intermediate — not [G, A, B, Q] — is the only rule-axis blowup; G is
    shape-bucketed to a power of two (api._bucket_tensors), so the
    clamped chunk always divides it."""
    g = subj.shape[0]
    a = subj.shape[1]
    b, q = peerq.shape[1], peerq.shape[2]
    c = min(chunk, g)
    none = jnp.int32(TIER_KEY_NONE)
    init = (
        jnp.full((a, b, q), none, dtype=jnp.int32),
        jnp.full((a, b, q), none, dtype=jnp.int32),
    )

    def body(carry, xs):
        s, pq, ka, kb = xs  # [c, A], [c, B, Q], [c], [c]
        m = s[:, :, None, None] & pq[:, None, :, :]  # [c, A, B, Q]
        a_min = jnp.min(jnp.where(m, ka[:, None, None, None], none), axis=0)
        b_min = jnp.min(jnp.where(m, kb[:, None, None, None], none), axis=0)
        return (
            jnp.minimum(carry[0], a_min),
            jnp.minimum(carry[1], b_min),
        ), None

    (anp_min, banp_min), _ = jax.lax.scan(
        body,
        init,
        (
            subj.reshape(g // c, c, a),
            peerq.reshape(g // c, c, b, q),
            anp_key.reshape(g // c, c),
            banp_key.reshape(g // c, c),
        ),
    )
    return anp_min, banp_min


def resolve_tier_lattice(
    np_allowed: jnp.ndarray,  # NetworkPolicy-tier verdict (any shape)
    has_target_b: jnp.ndarray,  # bool, broadcastable to np_allowed
    anp_min: jnp.ndarray,  # int32 min ANP key, same shape as np_allowed
    banp_min: jnp.ndarray,
) -> jnp.ndarray:
    """The lattice fold: ANP first-match (Allow/Deny final, Pass falls
    through), then the NetworkPolicy tier WHERE a target selects the pod
    (final), then BANP first-match, then default-allow.  np_allowed is
    the existing direction verdict (~has_target | any_allow): where
    has_target holds it equals the NP-tier verdict, and elsewhere it is
    bypassed, so the epilogue composes with every evaluator's existing
    output unchanged."""
    anp_act = jnp.where(anp_min < TIER_KEY_NONE, anp_min % 4, TIER_ACT_NONE)
    banp_act = jnp.where(banp_min < TIER_KEY_NONE, banp_min % 4, TIER_ACT_NONE)
    below = jnp.where(
        has_target_b,
        np_allowed,
        jnp.where(
            banp_act == TIER_ACT_NONE, True, banp_act == TIER_ACT_ALLOW
        ),
    )
    return jnp.where(
        (anp_act == TIER_ACT_NONE) | (anp_act == TIER_ACT_PASS),
        below,
        anp_act == TIER_ACT_ALLOW,
    )


def tier_direction_arrays(
    tenc: Dict[str, jnp.ndarray],
    selpod: jnp.ndarray,
    selns: jnp.ndarray,
    pod_ns_id: jnp.ndarray,
    q_port: jnp.ndarray,
    q_name: jnp.ndarray,
    q_proto: jnp.ndarray,
) -> Dict[str, jnp.ndarray]:
    """Per-direction tier precompute over ONE pod set (grid kernels use
    the same set for both sides): subj [G, N], peerq [G, N, Q], and the
    two [G] key vectors."""
    subj = tier_scope_match(
        tenc["subj_ns_sel"], tenc["subj_pod_kind"], tenc["subj_pod_sel"],
        selpod, selns, pod_ns_id,
    )
    peer = tier_scope_match(
        tenc["peer_ns_sel"], tenc["peer_pod_kind"], tenc["peer_pod_sel"],
        selpod, selns, pod_ns_id,
    )
    pport = port_spec_allows(tenc["port_spec"], q_port, q_name, q_proto)
    anp_key, banp_key = tier_keys(tenc)
    return {
        "subj": subj,
        "peerq": peer[:, :, None] & pport[:, None, :],
        "anp_key": anp_key,
        "banp_key": banp_key,
    }


@partial(jax.jit, static_argnames=("pack",))
def evaluate_grid_kernel(tensors: Dict, pack: bool = False) -> Dict[str, jnp.ndarray]:
    """Full-grid verdict on one device.

    tensors: pytree with keys
      sel_*: selector tables; pod_*: cluster pod arrays; ns_kv/ns_key;
      ingress/egress: per-direction encodings (dicts incl. peer_target);
      q_port/q_name/q_proto: [Q] port cases.
    `pack` (static; resolved by the caller via encoding.pack_enabled)
    routes the target-axis contraction through the 32-per-word packed
    bitmaps.  Returns ingress[q, d, s], egress[q, s, d],
    combined[q, s, d].
    """
    selpod = selector_match(
        tensors["sel_req_kv"],
        tensors["sel_exp_op"],
        tensors["sel_exp_key"],
        tensors["sel_exp_vals"],
        tensors["pod_kv"],
        tensors["pod_key"],
    )
    selns = selector_match(
        tensors["sel_req_kv"],
        tensors["sel_exp_op"],
        tensors["sel_exp_key"],
        tensors["sel_exp_vals"],
        tensors["ns_kv"],
        tensors["ns_key"],
    )

    out = {}
    for direction in ("ingress", "egress"):
        enc = tensors[direction]
        pre = direction_precompute(
            enc,
            selpod,
            selns,
            tensors["pod_ns_id"],
            tensors["pod_ip"],
            tensors["pod_ip_valid"],
        )
        peer_match = pre["peer_match"]
        if "host_ip_match" in enc:
            # patch host-evaluated ip-peer rows (IPv6 fallback)
            peer_match = jnp.where(
                enc["host_ip_mask"][:, None], enc["host_ip_match"], peer_match
            )
        pport = port_spec_allows(
            enc["port_spec"],
            tensors["q_port"],
            tensors["q_name"],
            tensors["q_proto"],
        )
        out[direction] = direction_allowed(
            pre["tmatch"], pre["has_target"], m_tp_onehot(enc), peer_match,
            pport, pack=pack,
        )
        if "tiers" in tensors:
            # precedence-tier resolution epilogue: same trace, one
            # device execution still (docs/DESIGN.md "Precedence tiers")
            ta = tier_direction_arrays(
                tensors["tiers"][direction],
                selpod,
                selns,
                tensors["pod_ns_id"],
                tensors["q_port"],
                tensors["q_name"],
                tensors["q_proto"],
            )
            anp_min, banp_min = tier_first_match_keys(
                ta["subj"], ta["peerq"], ta["anp_key"], ta["banp_key"]
            )
            out[direction] = resolve_tier_lattice(
                out[direction],
                pre["has_target"][:, None, None],
                anp_min,
                banp_min,
            )

    # ingress is indexed [dst, src, q]; egress [src, dst, q]
    combined = out["egress"] & jnp.swapaxes(out["ingress"], 0, 1)
    # [q, ., .] layout for the GridVerdict API; transposing here keeps the
    # whole evaluation a single device execution (each extra dispatch costs
    # a full round trip on a tunneled TPU).
    return {
        "ingress": jnp.moveaxis(out["ingress"], -1, 0),
        "egress": jnp.moveaxis(out["egress"], -1, 0),
        "combined": jnp.moveaxis(combined, -1, 0),
    }


@contracts.args(class_of="(N,) int32")
def gather_class_grids(
    out: Dict[str, jnp.ndarray], class_of: jnp.ndarray
) -> Dict[str, jnp.ndarray]:
    """Broadcast class-grid verdicts back to the full pod x pod grid.

    out: {ingress, egress, combined} [Q, C*, C*] bool over the (possibly
    bucketing-padded) class axes; class_of: [N] int32 pod -> class map
    (values < the real class count, so pad rows are never gathered).
    Two chained int32 gathers per grid — cell (q, i, j) copies class
    cell (q, class_of[i], class_of[j]), which is exact by the class
    signature's completeness (encoding.compute_pod_classes).  Designed
    to trace INSIDE the caller's jit so grid + gather stay one device
    execution."""

    def g(a: jnp.ndarray) -> jnp.ndarray:
        return jnp.take(jnp.take(a, class_of, axis=1), class_of, axis=2)

    return {k: g(v) for k, v in out.items()}


@jax.jit
def rule_firing_kernel(shared: Dict, enc: Dict) -> Dict[str, jnp.ndarray]:
    """Per-RULE firing-mask components for one direction — the batched
    variant of the verdict path that the analysis layer
    (cyclonus_tpu.analysis) audits on.

    The firing mask of flat peer rule p over (target-side pod n,
    peer-side pod m, port case q) is the rank-1 product

        fire[p, n, m, q] = rule_tmatch[p, n] & peer_match[p, m] & pport[p, q]

    so returning the three factors is the whole mask without ever
    materializing [P, N, N, Q].  rule_tmatch gathers each rule's
    target row (a rule fires only where its OWN target applies), with
    pad rules (peer_target -1) masked to all-False."""
    selpod = selector_match(
        shared["sel_req_kv"],
        shared["sel_exp_op"],
        shared["sel_exp_key"],
        shared["sel_exp_vals"],
        shared["pod_kv"],
        shared["pod_key"],
    )
    selns = selector_match(
        shared["sel_req_kv"],
        shared["sel_exp_op"],
        shared["sel_exp_key"],
        shared["sel_exp_vals"],
        shared["ns_kv"],
        shared["ns_key"],
    )
    pre = direction_precompute(
        enc,
        selpod,
        selns,
        shared["pod_ns_id"],
        shared["pod_ip"],
        shared["pod_ip_valid"],
    )
    peer_match = pre["peer_match"]
    if "host_ip_match" in enc:
        peer_match = jnp.where(
            enc["host_ip_mask"][:, None], enc["host_ip_match"], peer_match
        )
    pport = port_spec_allows(
        enc["port_spec"],
        shared["q_port"],
        shared["q_name"],
        shared["q_proto"],
    )
    pt = enc["peer_target"]
    rule_tmatch = jnp.take(pre["tmatch"], jnp.maximum(pt, 0), axis=0) & (
        pt >= 0
    )[:, None]
    return {
        "rule_tmatch": rule_tmatch,  # [P, N] bool
        "peer_match": peer_match,  # [P, N] bool
        "pport": pport,  # [P, Q] bool
        "has_target": pre["has_target"],  # [N] bool
    }


@jax.jit
def grid_stats_kernel(ingress, egress, combined) -> jnp.ndarray:
    """[3] f32 mean allow-rates — one execution, one scalar-sized
    transfer (vs three separate float() readbacks)."""
    return jnp.stack(
        [jnp.mean(ingress), jnp.mean(egress), jnp.mean(combined)]
    )
