"""Tiled/streaming verdict evaluation for grids too large to materialize.

The single-device kernel (kernel.py) holds three [Q, N, N] bool tables plus
an [N, N*Q] matmul intermediate in HBM at once — at 100k pods that is tens
of GB, far past a single chip.  This module evaluates the grid in
fixed-size SOURCE-ROW BLOCKS instead, in three modes:

  * counts  — the whole block loop runs DEVICE-SIDE inside one jit
              (lax.fori_loop), producing per-tile allow counts; one
              dispatch + one small readback total.  This matters on a
              tunneled TPU where every host<->device round trip costs
              ~100ms (measured) — a Python-loop design would pay that per
              tile.
  * blocks  — a Python generator yielding [B, N, Q] verdict blocks for
              streaming consumers (writers, row aggregations); one
              dispatch per tile, transfers dominated by the block fetch.
  * pairs   — point evaluation of arbitrary (src, dst) index pairs
              (evaluate_pairs_kernel); no N x N grid anywhere, so it
              scales to any cluster size — powers the large-scale parity
              spot checks (bench.py spot_check_pairs).

Decision procedure identical to kernel.py (reference policy.go:138-174);
parity is enforced by tests/test_engine_tiled.py against both the
single-device kernel and the scalar oracle.

Memory note: the target-allows tensors are precomputed once per direction
and stored as bf16 (ready for the MXU).  Matmul outputs use bf16
accumulation: inputs are 0/1, so every partial sum is a sum of nonnegative
values >= 1 at the first hit — rounding can never drive a positive count
to zero, so the `> 0` threshold stays exact.

Threading note (lock discipline, docs/DESIGN.md): everything here is
pure functions of explicit operands — no module-level mutable state, no
locks — by design.  All caching of these programs' operands (the pinned
precompute, the gathered slab operands) lives in api.TpuPolicyEngine,
where it is guarded by _slab_lock and checked by tools/locklint.py;
keep it that way rather than adding module-level caches here (a second
cache layer would need its own lock AND a consistent order against
_slab_lock to stay off the LK002 cycle graph).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Dict, Iterator, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..telemetry import instruments as ti
from ..utils import cachekeys
from ..utils.tracing import phase
from .encoding import TIER_KEY_NONE, pack_enabled
from .kernel import (
    direction_precompute,
    m_tp_onehot,
    pack_bool_words_jnp,
    packed_any,
    port_spec_allows,
    resolve_tier_lattice,
    selector_match,
    tier_direction_arrays,
    tier_first_match_keys,
)


def _apply_host_ip(enc: Dict, pre: Dict) -> Dict:
    if "host_ip_match" in enc:
        pre = dict(pre)
        pre["peer_match"] = jnp.where(
            enc["host_ip_mask"][:, None], enc["host_ip_match"], pre["peer_match"]
        )
    return pre


def _precompute(
    tensors: Dict, pack: bool = False
) -> Dict[str, Dict[str, jnp.ndarray]]:
    """Per-direction, port-resolved precompute shared by every tile:

      tallow_bf [T, N, Q] bf16 — target t allows traffic with pod n on the
                                 PEER side for port case q (m_tp @ peer_allow)
      tmatch    [T, N] bool    — target t applies to pod n (target side)
      has_target[N] bool

    With pack=True (static; docs/DESIGN.md "Bit-packed kernel") the
    target-axis operands ship 32-per-word instead: tallow_pk [W, N, Q]
    int32 and tmatch_pk [W, N] int32 REPLACE tallow_bf (W =
    encoding.packed_words(T)) — 16x fewer peer-bundle bytes on the ring
    and a 32x shallower contraction in every tile body.  The bool
    tmatch/has_target stay (they are small and the count masks and slab
    plan read them).
    """
    selpod = selector_match(
        tensors["sel_req_kv"],
        tensors["sel_exp_op"],
        tensors["sel_exp_key"],
        tensors["sel_exp_vals"],
        tensors["pod_kv"],
        tensors["pod_key"],
    )
    selns = selector_match(
        tensors["sel_req_kv"],
        tensors["sel_exp_op"],
        tensors["sel_exp_key"],
        tensors["sel_exp_vals"],
        tensors["ns_kv"],
        tensors["ns_key"],
    )
    out = {}
    q = tensors["q_port"].shape[0]
    for direction in ("ingress", "egress"):
        enc = tensors[direction]
        pre = direction_precompute(
            enc,
            selpod,
            selns,
            tensors["pod_ns_id"],
            tensors["pod_ip"],
            tensors["pod_ip_valid"],
        )
        pre = _apply_host_ip(enc, pre)
        pport = port_spec_allows(
            enc["port_spec"],
            tensors["q_port"],
            tensors["q_name"],
            tensors["q_proto"],
        )
        n_p, n = pre["peer_match"].shape
        peer_allow = (
            pre["peer_match"][:, :, None] & pport[:, None, :]
        ).reshape(n_p, n * q)  # shape: (P, NQ)
        tallow = jnp.matmul(
            m_tp_onehot(enc).astype(jnp.bfloat16),
            peer_allow.astype(jnp.bfloat16),
            preferred_element_type=jnp.bfloat16,
        )
        t = tallow.shape[0]
        out[direction] = {
            "tmatch": pre["tmatch"],
            "has_target": pre["has_target"],
        }
        if pack:
            tallow_b = (tallow > 0).reshape(t, n, q)
            out[direction]["tallow_pk"] = pack_bool_words_jnp(
                tallow_b
            )  # shape: (W, N, Q) int32
            out[direction]["tmatch_pk"] = pack_bool_words_jnp(
                pre["tmatch"]
            )  # shape: (W, N) int32
        else:
            out[direction]["tallow_bf"] = (
                (tallow > 0).astype(jnp.bfloat16).reshape(t, n, q)
            )
        if "tiers" in tensors:
            # precedence-tier precompute (docs/DESIGN.md "Precedence
            # tiers"): subj/peerq/keys ride next to tallow so every tile
            # body can run the first-match resolution epilogue
            out[direction]["tier"] = tier_direction_arrays(
                tensors["tiers"][direction],
                selpod,
                selns,
                tensors["pod_ns_id"],
                tensors["q_port"],
                tensors["q_name"],
                tensors["q_proto"],
            )
    return out


#: the dst-side bundle keys — the arrays the ring paths rotate with
#: ppermute.  Tier arrays indexed by the DST pod axis (egress peer side,
#: ingress target side) must ride the bundle or a rotated step would
#: resolve tiers against the wrong shard.
_DST_VIEW_KEYS = ("tallow_e", "tmatch_i", "has_i")
_DST_TIER_KEYS = ("tier_peerq_e", "tier_subj_i")


def _dst_bundle_keys(ring: Dict) -> Tuple[str, ...]:
    keys = _DST_VIEW_KEYS
    if "tier_peerq_e" in ring:
        keys = keys + _DST_TIER_KEYS
    return keys


def _ring_sweep(n_dev: int, ring: Dict, init, body):
    """THE double-buffered ring loop every 1-D ring path shares — the
    sync ring counts, the pipelined twin, and the sharded grid ring
    (sharded._ring_grid_eval) — so the schedule can never diverge
    between them.  One ppermute hop per step, ISSUED BEFORE the step's
    compute: the transfer and the compute both only read the current
    bundle, so the hop flies on ICI while the MXU contracts (one
    resident bundle + one in-flight).  `body(step, ring, acc) -> acc`
    consumes the bundle currently held.  All n_dev hops run — the final
    rotation returns every bundle to its origin; it is kept rather than
    guarded out because collectives under lax.cond don't lower
    reliably, it is one ICI transfer, and the pipelined twin RELIES on
    it to hand the bundle back for the next eval's donation.  Returns
    (acc, ring-at-origin)."""
    perm = [(d, (d + 1) % n_dev) for d in range(n_dev)]

    def ring_step(step, carry):
        acc, ring = carry
        nxt = jax.tree_util.tree_map(
            lambda x: jax.lax.ppermute(x, "x", perm), ring
        )
        acc = body(step, ring, acc)
        return acc, nxt

    return jax.lax.fori_loop(0, n_dev, ring_step, (init, ring))


def _split_pre(pre: Dict) -> Tuple[Dict, Dict]:
    """Split the per-direction precompute into the SRC-side view (the
    tile's source rows: egress target side + ingress peer side) and the
    DST-side view (egress peer side + ingress target side).  On a single
    device both views slice the same arrays; in the ring path the dst
    view is the rotating remote shard.  Tier arrays split the same way:
    subjects sit on the direction's target side, peerq on its peer side;
    the [G] key vectors are pod-independent and stay in the src view.

    The canonical view KEYS are representation-independent: with the
    packed precompute (tallow_pk/tmatch_pk present) the same names carry
    the int32 packed words — the bundle specs and ring schedules are
    shape-pattern-identical, and _tile_verdicts_split picks the
    contraction by dtype.  The packed bundle is what rides the ppermute
    ring: ~16x fewer peer bytes per hop than the bf16 tallow."""
    if "tallow_pk" in pre["egress"]:
        src = {
            "tmatch_e": pre["egress"]["tmatch_pk"],
            "has_e": pre["egress"]["has_target"],
            "tallow_i": pre["ingress"]["tallow_pk"],
        }
        dst = {
            "tallow_e": pre["egress"]["tallow_pk"],
            "tmatch_i": pre["ingress"]["tmatch_pk"],
            "has_i": pre["ingress"]["has_target"],
        }
    else:
        src = {
            "tmatch_e": pre["egress"]["tmatch"],
            "has_e": pre["egress"]["has_target"],
            "tallow_i": pre["ingress"]["tallow_bf"],
        }
        dst = {
            "tallow_e": pre["egress"]["tallow_bf"],
            "tmatch_i": pre["ingress"]["tmatch"],
            "has_i": pre["ingress"]["has_target"],
        }
    if "tier" in pre["egress"]:
        te, ti_ = pre["egress"]["tier"], pre["ingress"]["tier"]
        src["tier_subj_e"] = te["subj"]
        src["tier_peerq_i"] = ti_["peerq"]
        src["tier_keys_e"] = jnp.stack([te["anp_key"], te["banp_key"]])
        src["tier_keys_i"] = jnp.stack([ti_["anp_key"], ti_["banp_key"]])
        dst["tier_peerq_e"] = te["peerq"]
        dst["tier_subj_i"] = ti_["subj"]
    return src, dst


def _tile_verdicts_split(
    src: Dict, dst: Dict, start: jnp.ndarray, block: int
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Verdict blocks for source rows [start, start+block) of the src
    view against ALL dst-view pods: (ingress_rows, egress, combined),
    each [B, Nd, Q] bool; ingress_rows[b, d, q] = ingress verdict for
    dst d <- src (start+b).  THE per-tile verdict body — every tiled
    path (single-device, mesh-parallel, ring) goes through here so the
    semantics cannot diverge.  The contraction is picked by the view
    REPRESENTATION (_split_pre): int32 views are 32-per-word packed
    bitmaps contracted with packed_any; bool/bf16 views keep the bf16
    matmul.  Both forms are exact on 0/1 values, pinned bit-identical
    by the packed parity suite."""
    t_e, nd, q = dst["tallow_e"].shape
    t_i = dst["tmatch_i"].shape[0]
    packed = src["tmatch_e"].dtype == jnp.int32

    # egress: the source block is the TARGET side; peer side = dst pods
    tme = jax.lax.dynamic_slice(src["tmatch_e"], (0, start), (t_e, block))
    hte = jax.lax.dynamic_slice(src["has_e"], (start,), (block,))  # [B]
    if packed:
        any_e = packed_any(tme, dst["tallow_e"].reshape(t_e, nd * q))
    else:
        any_e = (
            jnp.matmul(
                tme.T.astype(jnp.bfloat16),
                dst["tallow_e"].reshape(t_e, nd * q),
                preferred_element_type=jnp.bfloat16,
            )
            > 0
        )
    any_e = any_e.reshape(block, nd, q)
    egress = (~hte[:, None, None]) | any_e  # [B, Nd, Q]

    # ingress: the source block is the PEER side; target side = dst pods
    tli = jax.lax.dynamic_slice(
        src["tallow_i"], (0, start, 0), (t_i, block, q)
    )  # [T, B, Q]
    if packed:
        any_i = packed_any(dst["tmatch_i"], tli.reshape(t_i, block * q))
    else:
        any_i = (
            jnp.matmul(
                dst["tmatch_i"].T.astype(jnp.bfloat16),
                tli.reshape(t_i, block * q),
                preferred_element_type=jnp.bfloat16,
            )
            > 0
        )
    any_i = any_i.reshape(nd, block, q)
    ingress_t = (~dst["has_i"][:, None, None]) | any_i  # [Nd, B, Q]

    if "tier_subj_e" in src:
        # precedence-tier resolution epilogue, per tile (docs/DESIGN.md
        # "Precedence tiers"): egress subjects are the source block,
        # ingress subjects the dst view — same first-match fold as the
        # full-grid kernel, over this tile's slices
        g_e = src["tier_subj_e"].shape[0]
        subj_e = jax.lax.dynamic_slice(
            src["tier_subj_e"], (0, start), (g_e, block)
        )  # [G, B]
        anp_e, banp_e = tier_first_match_keys(
            subj_e, dst["tier_peerq_e"], src["tier_keys_e"][0],
            src["tier_keys_e"][1],
        )  # [B, Nd, Q]
        egress = resolve_tier_lattice(
            egress, hte[:, None, None], anp_e, banp_e
        )
        g_i = src["tier_peerq_i"].shape[0]
        peerq_i = jax.lax.dynamic_slice(
            src["tier_peerq_i"], (0, start, 0), (g_i, block, q)
        )  # [G, B, Q]
        anp_i, banp_i = tier_first_match_keys(
            dst["tier_subj_i"], peerq_i, src["tier_keys_i"][0],
            src["tier_keys_i"][1],
        )  # [Nd, B, Q]
        ingress_t = resolve_tier_lattice(
            ingress_t, dst["has_i"][:, None, None], anp_i, banp_i
        )

    ingress_rows = jnp.swapaxes(ingress_t, 0, 1)  # [B, Nd, Q]
    combined = egress & ingress_rows
    return ingress_rows, egress, combined


def _tile_verdicts(
    pre: Dict, start: jnp.ndarray, block: int
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Single-array-set form of _tile_verdicts_split (src == dst)."""
    src, dst = _split_pre(pre)
    return _tile_verdicts_split(src, dst, start, block)


def _pad_pod_axis(tensors: Dict, n_pods: int, block: int) -> Tuple[Dict, int]:
    """Pad the pod axis to a multiple of `block` with inert rows (same
    scheme as sharded._pad_pod_arrays; padded rows match no target and no
    peer, so their verdicts are all-allow rows that get masked/stripped)."""
    from .sharded import _pad_pod_arrays

    # n_tiles comes from the FINAL padded length: the arrays may arrive
    # longer than n_pods from build-time shape bucketing
    tensors, padded = _pad_pod_arrays(tensors, n_pods, block)
    return tensors, padded // block


def _tile_counts_split(
    src: Dict,
    dst: Dict,
    src_valid: jnp.ndarray,
    dst_valid: jnp.ndarray,
    start,
    block: int,
) -> jnp.ndarray:
    """[3] int32 validity-masked allow counts for src-view rows
    [start, start+block) against all dst-view pods — THE per-tile count
    body, shared by the single-device, mesh-parallel, and ring paths so
    the masking/count semantics cannot diverge.  Safe in int32 for any
    block*Nd*Q that fits in HBM."""
    ingress_rows, egress, combined = _tile_verdicts_split(src, dst, start, block)
    sv = jax.lax.dynamic_slice(src_valid, (start,), (block,))
    mask = sv[:, None, None] & dst_valid[None, :, None]
    return jnp.stack(
        [
            jnp.sum(ingress_rows & mask, dtype=jnp.int32),
            jnp.sum(egress & mask, dtype=jnp.int32),
            jnp.sum(combined & mask, dtype=jnp.int32),
        ]
    )


def _tile_counts(pre: Dict, valid: jnp.ndarray, start, block: int) -> jnp.ndarray:
    """Single-array-set form of _tile_counts_split (src == dst)."""
    src, dst = _split_pre(pre)
    return _tile_counts_split(src, dst, valid, valid, start, block)


def _int32_safe_block(block: int, n_pods: int, q: int) -> int:
    """Halve the tile height until per-tile counts stay below 2^31."""
    while block > 1 and block * n_pods * q >= 2**31:
        block //= 2
    return block


@partial(jax.jit, static_argnames=("block", "n_tiles", "n_pods", "pack"))
def _counts_kernel(
    tensors: Dict, block: int, n_tiles: int, n_pods: int, pack: bool = False
) -> jnp.ndarray:
    """[n_tiles, 3] int32 allow counts (ingress, egress, combined) over the
    full grid, computed with one device execution; the host sums tiles in
    int64."""
    pre = _precompute(tensors, pack)
    n_padded = tensors["pod_ns_id"].shape[0]
    valid = jnp.arange(n_padded) < n_pods  # [N] pod-validity mask

    def body(i, counts):
        return counts.at[i].set(_tile_counts(pre, valid, i * block, block))

    counts = jnp.zeros((n_tiles, 3), dtype=jnp.int32)
    return jax.lax.fori_loop(0, n_tiles, body, counts)


def evaluate_grid_counts(
    tensors: Dict, n_pods: int, block: int = 1024, pack: bool = None
) -> Dict[str, int]:
    """Allow counts over the full N x N x Q grid without materializing it.
    One jit dispatch, one [n_tiles, 3] readback.  `pack` routes the tile
    bodies through the 32-per-word packed operands (None: resolve
    CYCLONUS_PACK eagerly here, outside the jit)."""
    if pack is None:
        pack = pack_enabled()
    q = int(tensors["q_port"].shape[0])
    # per-tile counts are int32: keep block * N * Q below 2^31 (the
    # equivalent global-accumulator overflow bit the pallas backend at
    # 100k pods before partials were introduced)
    block = _int32_safe_block(min(block, max(n_pods, 1)), n_pods, q)
    with ti.eval_flight("counts.xla", n_pods, q, block=block) as fl:
        tensors, n_tiles = _pad_pod_axis(tensors, n_pods, block)
        with phase("engine.dispatch"):
            out = _counts_kernel(tensors, block, n_tiles, n_pods, pack)
        # the readback is the execution barrier (dispatch is async)
        with phase("engine.execute"):
            counts = np.asarray(out, dtype=np.int64).sum(axis=0)
        total = q * n_pods * n_pods
        fl.set(cells=total)
    return {
        "ingress": int(counts[0]),
        "egress": int(counts[1]),
        "combined": int(counts[2]),
        "cells": total,
    }


# --- equivalence-class (compressed-grid) counts ---------------------------
#
# The compressed counts contract: with pods bucketed into C equivalence
# classes (encoding.compute_pod_classes), every full-grid count is the
# class-grid count weighted by class sizes:
#
#     count[q] = sum_{c1, c2} verdict[q, c1, c2] * size[c1] * size[c2]
#
# Exactness without float64 (disabled by default in JAX) is a two-stage
# split: the DEVICE computes per-src-class weighted row sums
# rs[c, q, k] = sum_dst verdict * w[dst] — every partial sum is an
# integer <= N, exact in f32 while N < 2^24 (api gates the path on that
# bound) — and the HOST finishes sum_c w[c] * rs[c] in int64, where the
# ~1e12-scale products live.  The [Q, C, C] verdict grid never
# materializes: the same _tile_verdicts_split body every dense tiled
# path uses runs per class tile, with the count epilogue swapped for
# the weighted row-sum einsum.


def _class_tile_rowsums(
    src: Dict, dst: Dict, w_dst: jnp.ndarray, start, block: int
) -> jnp.ndarray:
    """[block, Q, 3] f32 dst-weighted verdict row sums for src-view rows
    [start, start+block): rs[b, q, k] = sum_dst grid_k * w_dst.  Pad
    classes carry weight 0 on the dst side and are zeroed by the host
    weighting on the src side, so no validity mask is needed."""
    ingress_rows, egress, combined = _tile_verdicts_split(src, dst, start, block)

    def rs(a: jnp.ndarray) -> jnp.ndarray:
        # HIGHEST precision is load-bearing: TPU's default f32 matmul
        # runs bf16 multiplies, which round class-size weights > 256
        # (e.g. a 1955-pod class -> 1952) and would silently break the
        # exact-integer contract class_counts_finish rounds on.  CPU
        # (where the parity suites run) is exact either way — only the
        # TPU mega shapes would see the corruption.
        return jnp.einsum(
            "bdq,d->bq",
            a.astype(jnp.float32),
            w_dst,
            precision=jax.lax.Precision.HIGHEST,
        )

    return jnp.stack([rs(ingress_rows), rs(egress), rs(combined)], axis=-1)


@partial(jax.jit, static_argnames=("block", "n_tiles", "pack"))
def _class_rowsums_kernel(
    tensors: Dict, w: jnp.ndarray, block: int, n_tiles: int, pack: bool = False
) -> jnp.ndarray:
    """[n_tiles * block, Q, 3] f32 weighted row sums over the class grid,
    one device execution (fori_loop over class tiles)."""
    pre = _precompute(tensors, pack)
    src, dst = _split_pre(pre)
    q = tensors["q_port"].shape[0]

    def body(i, out):
        rs = _class_tile_rowsums(src, dst, w, i * block, block)
        return jax.lax.dynamic_update_slice(out, rs, (i * block, 0, 0))

    out = jnp.zeros((n_tiles * block, q, 3), dtype=jnp.float32)
    return jax.lax.fori_loop(0, n_tiles, body, out)


def class_rowsums_plan(
    tensors: Dict, n_classes: int, class_size: np.ndarray, block: int = 1024
):
    """(w, block, n_tiles) for the class row-sum kernel over `tensors`
    whose pod axis is the (bucketing-padded) class axis.  Bucketed axes
    (api._bucket_pods) are powers of two or multiples of 1024, so
    min(block, 1024, cb) always divides cb; the fallback to the whole
    axis covers hand-built tensor dicts only."""
    cb = int(tensors["pod_ns_id"].shape[0])
    block = max(1, min(block, 1024, cb))
    if cb % block:
        block = cb
    w = np.zeros((cb,), dtype=np.float32)
    w[:n_classes] = np.asarray(class_size, dtype=np.float32)
    return w, block, cb // block


def class_counts_finish(
    rowsums: np.ndarray,
    class_size: np.ndarray,
    n_classes: int,
    q: int,
    n_pods: int,
) -> Dict[str, int]:
    """Exact int64 host finish of the device row sums: the src-side
    class weighting.  Row-sum entries are integers <= N held exactly in
    f32 (N < 2^24 gated by the caller); the products reach ~N^2 and live
    in int64 only."""
    rs = np.rint(np.asarray(rowsums)[:n_classes]).astype(np.int64)  # [C, Q, 3]
    w = np.asarray(class_size, dtype=np.int64)
    totals = (w[:, None, None] * rs).sum(axis=(0, 1))  # [3]
    return {
        "ingress": int(totals[0]),
        "egress": int(totals[1]),
        "combined": int(totals[2]),
        "cells": q * n_pods * n_pods,
    }


@partial(jax.jit, static_argnames=("interpret",))
def _class_rowsums_fused_kernel(
    tensors: Dict, w: jnp.ndarray, interpret: bool = False
) -> jnp.ndarray:
    """Fused-epilogue twin of _class_rowsums_kernel: packed precompute +
    the packed Pallas kernel whose EPILOGUE computes the dst-weighted
    row sums in VMEM (the class-compression gather's weighting never
    round-trips a verdict block through HBM).  One jit: precompute +
    kernel are one device execution.  Returns [Cb, Q, 3] f32 —
    bit-identical to the split kernel by the fused-vs-split parity
    test."""
    from .pallas_kernel import verdict_counts_pallas_packed

    pre = _precompute(tensors, True)
    tier = {
        d: pre[d]["tier"] for d in ("ingress", "egress")
    } if "tier" in pre["egress"] else None
    cb = int(tensors["pod_ns_id"].shape[0])
    rs = verdict_counts_pallas_packed(
        pre["egress"]["tmatch_pk"],
        pre["egress"]["has_target"],
        pre["egress"]["tallow_pk"],
        pre["ingress"]["tmatch_pk"],
        pre["ingress"]["has_target"],
        pre["ingress"]["tallow_pk"],
        n_pods=cb,  # every class row is live; pad weights are zero
        tier=tier,
        w_dst=w,
        interpret=interpret,
    )  # [Q, Cb', 3] f32
    return jnp.moveaxis(rs[:, :cb, :], 0, 1)  # [Cb, Q, 3]


def evaluate_grid_counts_classes(
    tensors: Dict,
    n_classes: int,
    class_size: np.ndarray,
    n_pods: int,
    block: int = 1024,
    pack: bool = None,
    kernel: str = None,
) -> Tuple[Dict[str, int], float]:
    """Allow counts over the FULL N x N x Q grid, evaluated on the
    compressed C x C class grid and weighted back exactly.  Returns
    (counts, gather_s) where gather_s is the broadcast-back epilogue
    (the host weighting) — the cheap gather the compression trades the
    dense grid for.

    kernel="pallas" (the TPU default when `pack` is on) runs the FUSED
    packed kernel — contraction + tier lattice + the dst-weighted gather
    epilogue in one Pallas program; kernel="xla" keeps the fori_loop
    tile body.  Identical row sums by construction (the fused-vs-split
    parity test pins them)."""
    import time as _time

    from .pallas_kernel import packed_tier_eligible

    if pack is None:
        pack = pack_enabled()
    if kernel is None:
        # the same static-unroll ceiling the dense counts route
        # enforces (api._packed_tier_ok): an oversized tier rule axis
        # routes the class counts to the XLA tile loop too
        kernel = (
            "pallas"
            if pack
            and jax.default_backend() == "tpu"
            and packed_tier_eligible(tensors)
            else "xla"
        )
    if kernel not in ("pallas", "xla"):
        raise ValueError(
            f"unknown class counts kernel {kernel!r} (want 'pallas' or 'xla')"
        )
    if kernel == "pallas" and not packed_tier_eligible(tensors):
        raise ValueError(
            "class counts kernel 'pallas' cannot fuse a tier rule axis "
            "past the static-unroll ceiling; use kernel='xla' or "
            "kernel=None (auto)"
        )
    q = int(tensors["q_port"].shape[0])
    w, block, n_tiles = class_rowsums_plan(tensors, n_classes, class_size, block)
    with ti.eval_flight(
        "counts.classes", n_pods, q, classes=n_classes, block=block
    ) as fl:
        with phase("engine.dispatch"):
            if kernel == "pallas":
                from .pallas_kernel import _should_interpret

                out = _class_rowsums_fused_kernel(
                    tensors, w, interpret=_should_interpret()
                )
            else:
                out = _class_rowsums_kernel(tensors, w, block, n_tiles, pack)
        # the readback is the execution barrier (dispatch is async)
        with phase("engine.execute"):
            rs = np.asarray(out)
        t0 = _time.perf_counter()
        counts = class_counts_finish(rs, class_size, n_classes, q, n_pods)
        gather_s = _time.perf_counter() - t0
        fl.set(cells=counts["cells"])
    return counts, gather_s


def evaluate_grid_counts_classes_sharded(
    tensors: Dict,
    n_classes: int,
    class_size: np.ndarray,
    n_pods: int,
    block: int = 1024,
    mesh=None,
) -> Tuple[Dict[str, int], float]:
    """Mesh-parallel compressed counts: the CLASS axis (already tiny
    next to the pod axis) splits over the mesh, each device computes the
    weighted row sums for its class shard against the replicated dst
    view, and one all-gather hands the [C, Q, 3] row sums to the same
    exact host finish as the single-device path."""
    import time as _time

    from jax.sharding import PartitionSpec as P

    from .sharded import mesh_device_context, shard_map_no_check

    mesh, n_dev, q, block, tensors, n_padded = _mesh_counts_setup(
        tensors, n_classes, block, mesh
    )
    pack = pack_enabled()
    shard = n_padded // n_dev
    tiles_per_shard = shard // block
    w = np.zeros((n_padded,), dtype=np.float32)
    w[:n_classes] = np.asarray(class_size, dtype=np.float32)
    t = dict(tensors)
    t["class_w"] = w

    def per_device(td):
        w_all = td["class_w"]
        pre = _precompute(
            {k: v for k, v in td.items() if k != "class_w"}, pack
        )
        src, dst = _split_pre(pre)
        dev = jax.lax.axis_index("x")
        row0 = dev * shard

        def body(i, out):
            rs = _class_tile_rowsums(src, dst, w_all, row0 + i * block, block)
            return jax.lax.dynamic_update_slice(out, rs, (i * block, 0, 0))

        out = jax.lax.fori_loop(
            0,
            tiles_per_shard,
            body,
            jnp.zeros((shard, q, 3), dtype=jnp.float32),
        )
        return jax.lax.all_gather(out, "x", axis=0, tiled=True)

    in_specs = jax.tree_util.tree_map(lambda _: P(), t)
    fn = jax.jit(
        shard_map_no_check(
            per_device, mesh=mesh, in_specs=(in_specs,), out_specs=P()
        )
    )
    with ti.eval_flight(
        "counts.classes.sharded",
        n_pods,
        q,
        classes=n_classes,
        devices=int(n_dev),
    ) as fl:
        with mesh_device_context(mesh):
            rs = np.asarray(fn(t))
        t0 = _time.perf_counter()
        counts = class_counts_finish(rs, class_size, n_classes, q, n_pods)
        gather_s = _time.perf_counter() - t0
        fl.set(cells=counts["cells"])
    return counts, gather_s


@partial(jax.jit, static_argnames=("block",))
def _block_kernel(pre: Dict, start: jnp.ndarray, block: int):
    return _tile_verdicts(pre, start, block)


def iter_grid_blocks(
    tensors: Dict, n_pods: int, block: int = 1024, pack: bool = None
) -> Iterator[Tuple[int, np.ndarray, np.ndarray, np.ndarray]]:
    """Stream verdict blocks to the host: yields
    (start, ingress_rows, egress, combined) with arrays [b, N, Q] bool,
    pad rows/columns already stripped.  ingress_rows[b, d, q] is the
    ingress verdict for dst d <- src (start+b) — i.e. full-grid
    ingress[q, d, start+b]."""
    if pack is None:
        pack = pack_enabled()
    block = min(block, max(n_pods, 1))
    tensors, n_tiles = _pad_pod_axis(tensors, n_pods, block)
    pre = _precompute_jit(tensors, pack)
    # the pod axis may carry MORE pad rows than one block's worth (shape
    # bucketing pads before this function): iterate only the tiles with
    # real rows and clamp the final tile's height to the real pod count
    n_tiles = min(n_tiles, -(-n_pods // block))
    for i in range(n_tiles):
        start = i * block
        ingress_rows, egress, combined = _block_kernel(
            pre, jnp.int32(start), block
        )
        b = min(block, n_pods - start)
        yield (
            start,
            np.asarray(ingress_rows)[:b, :n_pods],
            np.asarray(egress)[:b, :n_pods],
            np.asarray(combined)[:b, :n_pods],
        )


_precompute_jit = partial(jax.jit, static_argnames=("pack",))(_precompute)


def _mesh_counts_setup(tensors: Dict, n_pods: int, block: int, mesh):
    """Shared mesh/count-path setup: resolve the mesh, bound the tile
    height for int32 partials, and pad the pod axis so every device gets
    a whole number of tiles."""
    from .sharded import _pad_pod_arrays, default_mesh

    mesh = mesh or default_mesh()
    n_dev = mesh.devices.size
    q = int(tensors["q_port"].shape[0])
    block = _int32_safe_block(min(block, max(n_pods // n_dev, 1)), n_pods, q)
    tensors, n_padded = _pad_pod_arrays(tensors, n_pods, n_dev * block)
    return mesh, n_dev, q, block, tensors, n_padded


def _run_mesh_counts(
    per_device, mesh, in_specs, tensors: Dict, q: int, n_pods: int,
    path: str = "counts.mesh",
) -> Dict[str, int]:
    """Shared tail of every mesh count path: one shard_map execution,
    then the int64 host sum of the [*, 3] int32 partials (device-side
    int64 silently truncates without jax_enable_x64).  `path` labels the
    telemetry flight entry with the calling mesh strategy."""
    from jax.sharding import PartitionSpec as P

    from .sharded import mesh_device_context, shard_map_no_check

    fn = jax.jit(
        shard_map_no_check(
            per_device, mesh=mesh, in_specs=(in_specs,), out_specs=P()
        )
    )
    with ti.eval_flight(
        path, n_pods, q, devices=int(mesh.devices.size)
    ) as fl:
        with mesh_device_context(mesh):
            counts = np.asarray(fn(tensors), dtype=np.int64).sum(axis=0)
        fl.set(cells=q * n_pods * n_pods)
    return {
        "ingress": int(counts[0]),
        "egress": int(counts[1]),
        "combined": int(counts[2]),
        "cells": q * n_pods * n_pods,
    }


def evaluate_grid_counts_ring(
    tensors: Dict, n_pods: int, block: int = 1024, mesh=None
) -> Dict[str, int]:
    """Ring-rotation counts: BOTH pod axes stay sharded.

    evaluate_grid_counts_sharded replicates the dst-side precompute
    (tallow is [T, N, Q] bf16 — the memory ceiling at large N); here each
    device keeps only its OWN pod shard's precompute, and the dst-side
    block rotates around the ring with jax.lax.ppermute, one hop per
    step — structurally the ring-attention/blockwise pattern from
    SURVEY.md §5 with verdict tiles in place of attention blocks:

        for step in range(n_dev):
            counts += local_src_rows x current_dst_block   (MXU tiles)
            dst_block <- left neighbor                      (ICI ppermute)

    Per-device memory is O(N/n_dev) instead of O(N), so max cluster size
    scales linearly with the mesh.  The rotating state is the
    (tallow_e, tmatch_i, has_i, tallow_i, tmatch_e-free) dst bundle; the
    ppermute overlaps with the next step's tile matmuls under XLA's
    scheduler."""
    from .sharded import pod_sharded_in_specs

    mesh, n_dev, q, block, tensors, n_padded = _mesh_counts_setup(
        tensors, n_pods, block, mesh
    )
    pack = pack_enabled()
    shard = n_padded // n_dev
    tiles_per_shard = shard // block

    def per_device(t):
        # local precompute over THIS device's pod shard only (t's pod
        # arrays arrive shard-sharded via in_specs); with packing on the
        # rotating dst bundle carries the packed words — the ppermute
        # hop moves ~16x fewer bytes per step
        pre = _precompute(t, pack)
        dev = jax.lax.axis_index("x")
        row0 = dev * shard
        valid_local = (jnp.arange(shard) + row0) < n_pods  # [shard]

        # src view stays local; the dst view (+ its validity mask) is the
        # rotating ring bundle, seeded with our own shard's dst-side view
        src, dst0 = _split_pre(pre)
        ring = dict(dst0, valid=valid_local)

        def body(step, ring, counts):
            dst = {k: ring[k] for k in _dst_bundle_keys(ring)}

            def tile(i, counts):
                row = _tile_counts_split(
                    src, dst, valid_local, ring["valid"], i * block, block
                )
                return counts.at[step * tiles_per_shard + i].set(row)

            return jax.lax.fori_loop(0, tiles_per_shard, tile, counts)

        counts = jnp.zeros((n_dev * tiles_per_shard, 3), dtype=jnp.int32)
        counts, _ = _ring_sweep(n_dev, ring, counts, body)
        return jax.lax.all_gather(counts, "x", axis=0, tiled=True)

    return _run_mesh_counts(
        per_device, mesh, pod_sharded_in_specs(tensors), tensors, q, n_pods,
        path="counts.ring",
    )


# --- double-buffered pipelined ring counts --------------------------------
#
# The sync ring path re-transfers the host tensors and re-derives the
# peer-side bundle every eval; at N chips the per-dispatch overhead is
# what the single-chip pipelined path already amortizes away (BENCH_r05:
# dispatch_overhead_s 0.09).  This twin splits the program in two:
#
#   seed(tensors) -> (src, ring)   one host->device transfer + the
#                                  per-shard precompute, device-resident
#   step(src, ring) -> (partials, ring)   the full n_dev-hop ring sweep;
#                                  the `ring` argument is DONATED, and
#                                  the final hop returns every bundle to
#                                  its origin, so the output ring aliases
#                                  the input's buffers — the rotating
#                                  peer slabs stream in place, no fresh
#                                  HBM per eval
#
# so steady-state mesh evals dispatch only `step`, back to back, with one
# readback (counts_pipelined_eval_s's discipline, on the mesh).

#: shard_map specs of the src-side (local, non-rotating) precompute
#: view.  Shape patterns are representation-independent: the packed
#: plan carries int32 word slabs ([W, N]/[W, N, Q]) under the same
#: keys and axis layout (_split_pre).
_SRC_SPECS = {
    "tmatch_e": P(None, "x"),  # shape: (T_e, N) bool | (W_e, N) int32
    "has_e": P("x"),  # shape: (N,) bool
    "tallow_i": P(None, "x", None),  # (T_i, N, Q) bf16 | (W_i, N, Q) int32
    "tier_subj_e": P(None, "x"),  # shape: (G_e, N) bool
    "tier_peerq_i": P(None, "x", None),  # shape: (G_i, N, Q) bool
    "tier_keys_e": P(),  # shape: (2, G_e) int32 (replicated)
    "tier_keys_i": P(),  # shape: (2, G_i) int32 (replicated)
}
#: shard_map specs of the rotating peer-side ring bundle (the arrays a
#: ppermute hop moves; donated by the step program)
_RING_SPECS = {
    "tallow_e": P(None, "x", None),  # (T_e, N, Q) bf16 | (W_e, N, Q) int32
    "tmatch_i": P(None, "x"),  # shape: (T_i, N) bool | (W_i, N) int32
    "has_i": P("x"),  # shape: (N,) bool
    "tier_peerq_e": P(None, "x", None),  # shape: (G_e, N, Q) bool
    "tier_subj_i": P(None, "x"),  # shape: (G_i, N) bool
    "valid": P("x"),  # shape: (N,) bool
}

_RING_PIPELINES: Dict = {}  # cache-key: mesh, shard, block, n_pods, tiered, pack, specs
_RING_PIPELINES_MAX = 32


def ring_counts_pipeline(tensors: Dict, n_pods: int, block: int, mesh):
    """(mesh, seed_fn, step_fn, meta) for the double-buffered ring
    counts pipeline over `tensors` (already padded by the caller via
    _mesh_counts_setup).  Programs are cached per (mesh, shapes,
    tiered) so repeat case sets and same-bucket resizes reuse the
    compiled pair."""
    from .sharded import pod_sharded_in_specs, shard_map_no_check

    pack = pack_enabled()
    n_dev = int(mesh.devices.size)
    n_padded = int(tensors["pod_ns_id"].shape[0])
    shard = n_padded // n_dev
    tiles_per_shard = shard // block
    tiered = "tiers" in tensors
    in_specs = pod_sharded_in_specs(tensors)
    leaves, treedef = jax.tree_util.tree_flatten(in_specs)
    key = (
        tuple(mesh.devices.flat),
        tuple(mesh.axis_names),
        shard,
        block,
        n_pods,
        tiered,
        pack,
        treedef,
        tuple(leaves),
    )
    cached = _RING_PIPELINES.get(key)
    if cached is not None:
        return cached

    def seed_device(t):
        pre = _precompute(t, pack)
        src, dst0 = _split_pre(pre)
        dev = jax.lax.axis_index("x")
        valid = (jnp.arange(shard) + dev * shard) < n_pods
        return src, dict(dst0, valid=valid)

    def step_device(src, ring):
        dev = jax.lax.axis_index("x")
        valid_local = (jnp.arange(shard) + dev * shard) < n_pods

        def body(step, ring, counts):
            dst = {k: ring[k] for k in _dst_bundle_keys(ring)}

            def tile(i, counts):
                row = _tile_counts_split(
                    src, dst, valid_local, ring["valid"], i * block, block
                )
                return counts.at[step * tiles_per_shard + i].set(row)

            return jax.lax.fori_loop(0, tiles_per_shard, tile, counts)

        counts = jnp.zeros((n_dev * tiles_per_shard, 3), dtype=jnp.int32)
        # the sweep's final hop returns every bundle to its origin,
        # which is what lets the caller feed the returned ring straight
        # back into the next (donated) step dispatch
        counts, ring = _ring_sweep(n_dev, ring, counts, body)
        return (
            jax.lax.all_gather(counts, "x", axis=0, tiled=True),
            ring,
        )

    src_specs = {
        k: v for k, v in _SRC_SPECS.items() if tiered or not k.startswith("tier")
    }
    ring_specs = {
        k: v
        for k, v in _RING_SPECS.items()
        if tiered or not k.startswith("tier")
    }
    seed_fn = jax.jit(
        shard_map_no_check(
            seed_device,
            mesh=mesh,
            in_specs=(in_specs,),
            out_specs=(src_specs, ring_specs),
        )
    )
    step_fn = jax.jit(
        shard_map_no_check(
            step_device,
            mesh=mesh,
            in_specs=(src_specs, ring_specs),
            out_specs=(P(), ring_specs),
        ),
        # the rotating peer buffers are DONATED: the returned (origin-
        # restored) bundle reuses their storage, so back-to-back step
        # dispatches stream the peer slabs through one double-buffered
        # allocation instead of allocating a bundle per eval
        donate_argnums=(1,),
    )
    out = (seed_fn, step_fn, {"shard": shard, "tiles": tiles_per_shard})
    if cachekeys.ACTIVE:
        cachekeys.register(
            "ring.pipelines",
            kind="program",
            components=cachekeys.program(
                "mesh", "shard", "block", "n_pods", "tiered", "pack", "specs"
            ),
        )
    if len(_RING_PIPELINES) >= _RING_PIPELINES_MAX:
        _RING_PIPELINES.clear()
    _RING_PIPELINES[key] = out
    return out


def evaluate_grid_counts_ring_pipelined(
    tensors: Dict,
    n_pods: int,
    reps: int = 10,
    block: int = 1024,
    mesh=None,
) -> Tuple[float, Dict[str, int]]:
    """Steady-state DEVICE-side seconds per ring-counts evaluation: one
    seed (transfer + precompute), then `reps` back-to-back step
    dispatches — the rotating peer bundle donated and fed forward — with
    ONE readback at the end, so per-eval cost excludes the per-dispatch
    host round trip (counts_pipelined_eval_s's discipline, on the mesh).
    Returns (seconds_per_eval, counts)."""
    import time as _time

    from .sharded import mesh_device_context

    mesh, n_dev, q, block, tensors, n_padded = _mesh_counts_setup(
        tensors, n_pods, block, mesh
    )
    seed_fn, step_fn, _meta = ring_counts_pipeline(
        tensors, n_pods, block, mesh
    )
    with ti.eval_flight(
        "counts.ring.pipelined", n_pods, q, devices=int(n_dev), reps=reps
    ) as fl:
        with mesh_device_context(mesh):
            src, ring = seed_fn(tensors)
            partials, ring = step_fn(src, ring)  # warm: compile + run
            np.asarray(partials)
            t0 = _time.perf_counter()
            for _ in range(max(reps, 1)):
                partials, ring = step_fn(src, ring)
            counts_np = np.asarray(partials)  # in-order stream: one barrier
            dt = (_time.perf_counter() - t0) / max(reps, 1)
        totals = counts_np.astype(np.int64).sum(axis=0)
        cells = q * n_pods * n_pods
        fl.set(cells=cells)
    counts = {
        "ingress": int(totals[0]),
        "egress": int(totals[1]),
        "combined": int(totals[2]),
        "cells": cells,
    }
    if dt > 0:
        ti.MESH_RING_STEP_SECONDS.set(dt / max(n_dev, 1))
    return dt, counts


def evaluate_grid_counts_ring2d(
    tensors: Dict, n_pods: int, block: int = 1024, mesh=None
) -> Dict[str, int]:
    """Hierarchical multi-host ring counts over a 2-D ("dcn", "ici") mesh.

    Same math as evaluate_grid_counts_ring — both pod axes sharded, the
    dst-side precompute bundle rotating — but the rotation is laid out
    for multi-host topology: of every `n_dev` hops, all but one ride the
    intra-host ICI ring; the bundle crosses the slow DCN boundary exactly
    once per host round.  Device (h, c) still sees every shard exactly
    once: at step j of round o it holds shard (h - o, c + o - j mod
    n_ici) — j sweeps the host's chips within a round, o sweeps the
    hosts — which enumerates the full (host, chip) torus.  The program
    is a lax.fori_loop over the n_dcn rounds with only the n_ici-step
    round body unrolled (collectives need static axis/perm, and a
    full-ring unroll would scale trace/compile size with total device
    count).

    This is the scale-out story the reference's slot map (SURVEY.md
    section 2.7/5) assigns to NCCL-style backends: XLA collectives over
    ICI within a host, DCN across hosts, no host-side communication
    code at all."""
    from jax.sharding import Mesh
    from jax.sharding import PartitionSpec as P

    from .sharded import default_mesh, pod_sharded_in_specs

    if mesh is None:
        # default: factor the flat device list into 2 "hosts" when even
        # (so the DCN axis actually exercises on a virtual mesh)
        devs = default_mesh().devices.reshape(-1)
        n_hosts = 2 if devs.size % 2 == 0 and devs.size > 1 else 1
        mesh = Mesh(devs.reshape(n_hosts, -1), ("dcn", "ici"))
    if set(mesh.axis_names) != {"dcn", "ici"}:
        raise ValueError(
            f"ring2d needs a ('dcn', 'ici') mesh, got {mesh.axis_names}"
        )
    mesh, n_dev, q, block, tensors, n_padded = _mesh_counts_setup(
        tensors, n_pods, block, mesh
    )
    n_dcn, n_ici = (
        mesh.shape["dcn"],
        mesh.shape["ici"],
    )
    pack = pack_enabled()
    shard = n_padded // n_dev
    tiles_per_shard = shard // block

    def per_device(t):
        pre = _precompute(t, pack)
        dev = jax.lax.axis_index("dcn") * n_ici + jax.lax.axis_index("ici")
        row0 = dev * shard
        valid_local = (jnp.arange(shard) + row0) < n_pods

        src, dst0 = _split_pre(pre)
        ring = dict(dst0, valid=valid_local)
        counts = jnp.zeros((n_dev * tiles_per_shard, 3), dtype=jnp.int32)

        def _hop(ring, axis, size):
            perm = [(d, (d + 1) % size) for d in range(size)]
            return jax.tree_util.tree_map(
                lambda x: jax.lax.ppermute(x, axis, perm), ring
            )

        def round_body(o, carry):
            counts, ring = carry
            # only the n_ici-step round body is traced; rounds ride the
            # fori_loop so program size is independent of the host count
            for j in range(n_ici):
                dst = {k: ring[k] for k in _dst_bundle_keys(ring)}

                def tile(i, counts, _dst=dst, _rv=ring["valid"], _j=j):
                    row = _tile_counts_split(
                        src, _dst, valid_local, _rv, i * block, block
                    )
                    return counts.at[
                        (o * n_ici + _j) * tiles_per_shard + i
                    ].set(row)

                counts = jax.lax.fori_loop(0, tiles_per_shard, tile, counts)
                # all-but-one hop per round stays on ICI; the bundle
                # crosses DCN once per round.  The last round's DCN hop
                # is wasted work but kept unconditional: collectives
                # under lax.cond don't lower reliably, and it is one
                # transfer per run.
                if j < n_ici - 1:
                    ring = _hop(ring, "ici", n_ici)
                else:
                    ring = _hop(ring, "dcn", n_dcn)
            return counts, ring

        counts, _ = jax.lax.fori_loop(0, n_dcn, round_body, (counts, ring))
        return jax.lax.all_gather(
            jax.lax.all_gather(counts, "ici", axis=0, tiled=True),
            "dcn",
            axis=0,
            tiled=True,
        )

    # pod arrays shard over the flattened (dcn, ici) device order
    in_specs = pod_sharded_in_specs(tensors)

    def _flatten_spec(spec):
        if spec and spec != P():
            parts = tuple(
                ("dcn", "ici") if p == "x" else p for p in spec
            )
            return P(*parts)
        return spec

    in_specs = jax.tree_util.tree_map(
        _flatten_spec, in_specs, is_leaf=lambda x: isinstance(x, P)
    )
    return _run_mesh_counts(
        per_device, mesh, in_specs, tensors, q, n_pods, path="counts.ring2d"
    )


def evaluate_grid_counts_sharded(
    tensors: Dict, n_pods: int, block: int = 1024, mesh=None, kernel: str = None
) -> Dict[str, int]:
    """Mesh-parallel tiled counts: the SOURCE-ROW axis is split over the
    mesh; each device evaluates its own row shard against the full
    (replicated) per-direction precompute, and the per-device partials
    are combined with one all-gather.  Combines the two scale axes:
    tiling lifts the per-device HBM ceiling, sharding divides wall-clock
    by the mesh size (tiles are embarrassingly parallel across source
    rows).

    kernel="pallas" runs the fused rectangular verdict+count kernel per
    device (src = the device's row shard, dst = the full axis) — the
    same program the single-chip fast path uses, so its measured
    per-device rates carry over; kernel="xla" runs the lax.fori_loop
    tile loop.  The default picks pallas on TPU, xla elsewhere (where
    pallas would run in slow interpret mode), mirroring
    api.evaluate_grid_counts.  Identical counts by construction; the
    mesh tests pin all of them against the single-device kernel.

    The per-pod precompute (selector matches, tallow) is evaluated
    replicated — it is O(N), negligible next to the O(N^2) grid."""
    if kernel is None:
        kernel = "pallas" if jax.default_backend() == "tpu" else "xla"
    if kernel not in ("pallas", "xla"):
        raise ValueError(
            f"unknown sharded counts kernel {kernel!r} (want 'pallas' or 'xla')"
        )
    from . import planspec

    if kernel == "pallas":
        planspec.record("counts.sharded.pallas")
    else:
        planspec.record("counts.sharded.xla")
    mesh, n_dev, q, block, tensors, n_padded = _mesh_counts_setup(
        tensors, n_pods, block, mesh
    )
    pack = pack_enabled()
    tiles_per_dev = n_padded // (n_dev * block)
    shard = n_padded // n_dev

    def per_device(t):
        pre = _precompute(t, pack)
        # this device's source-row range
        dev = jax.lax.axis_index("x")
        row0 = dev * tiles_per_dev * block
        valid = jnp.arange(n_padded) < n_pods

        if kernel == "pallas":
            from .pallas_kernel import (
                _should_interpret,
                verdict_counts_pallas_packed,
                verdict_counts_pallas_rect,
            )

            e, ig = pre["egress"], pre["ingress"]
            sl = partial(jax.lax.dynamic_slice_in_dim, start_index=row0)
            if pack:
                # packed rect form: src = this device's row shard, dst =
                # the full axis; the packed words slice on the pod axis
                # exactly like the dense operands
                partials = verdict_counts_pallas_packed(
                    sl(e["tmatch_pk"], slice_size=shard, axis=1),
                    sl(e["has_target"], slice_size=shard, axis=0),
                    e["tallow_pk"],
                    ig["tmatch_pk"],
                    ig["has_target"],
                    sl(ig["tallow_pk"], slice_size=shard, axis=1),
                    valid_src=sl(valid, slice_size=shard, axis=0),
                    valid_dst=valid,
                    interpret=_should_interpret(),
                )
            else:
                partials = verdict_counts_pallas_rect(
                    sl(e["tmatch"], slice_size=shard, axis=1),
                    sl(e["has_target"], slice_size=shard, axis=0),
                    e["tallow_bf"],
                    ig["tmatch"],
                    ig["has_target"],
                    sl(ig["tallow_bf"], slice_size=shard, axis=1),
                    valid_src=sl(valid, slice_size=shard, axis=0),
                    valid_dst=valid,
                    interpret=_should_interpret(),
                )  # [Q, n_src_tiles_local, 3]
            return jax.lax.all_gather(
                partials.reshape(-1, 3), "x", axis=0, tiled=True
            )

        def body(i, counts):
            return counts.at[i].set(
                _tile_counts(pre, valid, row0 + i * block, block)
            )

        counts = jax.lax.fori_loop(
            0,
            tiles_per_dev,
            body,
            jnp.zeros((tiles_per_dev, 3), dtype=jnp.int32),
        )
        # one collective: gather every device's per-tile partials so the
        # host can sum them in int64 (device int32 would overflow first)
        return jax.lax.all_gather(counts, "x", axis=0, tiled=True)

    from jax.sharding import PartitionSpec as P

    in_specs = jax.tree_util.tree_map(lambda _: P(), tensors)
    return _run_mesh_counts(
        per_device, mesh, in_specs, tensors, q, n_pods,
        path="counts.sharded",
    )


@jax.jit
def evaluate_pairs_kernel(
    tensors: Dict, s_idx: jnp.ndarray, d_idx: jnp.ndarray
) -> Dict[str, jnp.ndarray]:
    """Point verdicts for K (src, dst) index pairs: returns
    {ingress, egress, combined}, each [K, Q] bool.  O((S+T+P) * K) — no
    N x N grid anywhere; the scale-parity spot check rides this."""
    pod_kv = tensors["pod_kv"]
    pod_key = tensors["pod_key"]

    def sub(idx):
        return {
            "pod_kv": jnp.take(pod_kv, idx, axis=0),
            "pod_key": jnp.take(pod_key, idx, axis=0),
            "pod_ns_id": jnp.take(tensors["pod_ns_id"], idx, axis=0),
            "pod_ip": jnp.take(tensors["pod_ip"], idx, axis=0),
            "pod_ip_valid": jnp.take(tensors["pod_ip_valid"], idx, axis=0),
        }

    selns = selector_match(
        tensors["sel_req_kv"],
        tensors["sel_exp_op"],
        tensors["sel_exp_key"],
        tensors["sel_exp_vals"],
        tensors["ns_kv"],
        tensors["ns_key"],
    )

    def direction_pair(direction, t_idx, p_idx):
        """Verdict [K, Q] for (target-side pods t_idx, peer-side pods
        p_idx) in the given direction."""
        enc = tensors[direction]
        t_sub, p_sub = sub(t_idx), sub(p_idx)
        sel_t = selector_match(
            tensors["sel_req_kv"],
            tensors["sel_exp_op"],
            tensors["sel_exp_key"],
            tensors["sel_exp_vals"],
            t_sub["pod_kv"],
            t_sub["pod_key"],
        )
        sel_p = selector_match(
            tensors["sel_req_kv"],
            tensors["sel_exp_op"],
            tensors["sel_exp_key"],
            tensors["sel_exp_vals"],
            p_sub["pod_kv"],
            p_sub["pod_key"],
        )
        pre_t = direction_precompute(
            enc, sel_t, selns, t_sub["pod_ns_id"], t_sub["pod_ip"],
            t_sub["pod_ip_valid"],
        )
        pre_p = direction_precompute(
            enc, sel_p, selns, p_sub["pod_ns_id"], p_sub["pod_ip"],
            p_sub["pod_ip_valid"],
        )
        # host-evaluated ip-peer rows are indexed by ORIGINAL pod row
        if "host_ip_match" in enc:
            patch = jnp.take(enc["host_ip_match"], p_idx, axis=1)
            pre_p["peer_match"] = jnp.where(
                enc["host_ip_mask"][:, None], patch, pre_p["peer_match"]
            )
        pport = port_spec_allows(
            enc["port_spec"],
            tensors["q_port"],
            tensors["q_name"],
            tensors["q_proto"],
        )
        peer_allow = pre_p["peer_match"][:, :, None] & pport[:, None, :]  # [P,K,Q]
        # tallow[t, k, q] = any peer of target t allows peer-side pod k
        tallow = (
            jnp.einsum(
                "tp,pkq->tkq",
                m_tp_onehot(enc).astype(jnp.bfloat16),
                peer_allow.astype(jnp.bfloat16),
            )
            > 0
        )
        any_allow = jnp.einsum(
            "tk,tkq->kq",
            pre_t["tmatch"].astype(jnp.bfloat16),
            tallow.astype(jnp.bfloat16),
        ) > 0
        allowed = (~pre_t["has_target"][:, None]) | any_allow
        if "tiers" in tensors:
            # precedence-tier epilogue for point pairs: subject over the
            # target-side pods, peer over the peer-side pods, aligned
            # per pair k — [G, K] masks, no grid anywhere
            from .kernel import tier_keys, tier_scope_match

            tenc = tensors["tiers"][direction]
            subj = tier_scope_match(
                tenc["subj_ns_sel"], tenc["subj_pod_kind"],
                tenc["subj_pod_sel"], sel_t, selns, t_sub["pod_ns_id"],
            )  # [G, K]
            peer = tier_scope_match(
                tenc["peer_ns_sel"], tenc["peer_pod_kind"],
                tenc["peer_pod_sel"], sel_p, selns, p_sub["pod_ns_id"],
            )  # [G, K]
            pport_t = port_spec_allows(
                tenc["port_spec"],
                tensors["q_port"],
                tensors["q_name"],
                tensors["q_proto"],
            )  # [G, Q]
            match = (subj & peer)[:, :, None] & pport_t[:, None, :]  # [G,K,Q]
            anp_key, banp_key = tier_keys(tenc)
            none = jnp.int32(TIER_KEY_NONE)
            anp_min = jnp.min(
                jnp.where(match, anp_key[:, None, None], none), axis=0
            )
            banp_min = jnp.min(
                jnp.where(match, banp_key[:, None, None], none), axis=0
            )
            allowed = resolve_tier_lattice(
                allowed, pre_t["has_target"][:, None], anp_min, banp_min
            )
        return allowed

    egress = direction_pair("egress", s_idx, d_idx)  # src is target side
    ingress = direction_pair("ingress", d_idx, s_idx)  # dst is target side
    return {"ingress": ingress, "egress": egress, "combined": ingress & egress}
