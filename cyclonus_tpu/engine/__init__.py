"""The TPU engine: compiles a resolved matcher Policy + cluster model into
dense tensors and evaluates the full ingress+egress verdict grid as JAX
kernels (reference counterpart: the sequential loop in
pkg/connectivity/probe/jobrunner.go:68-94 + pkg/matcher/policy.go:131-174).

Pipeline:
  encoding.py  - host-side tensor compiler (numpy): vocab-encode labels,
                 selectors, targets, peers, port specs
  kernel.py    - jit/vmap verdict kernels (single device)
  sharded.py   - Mesh + shard_map source-axis-sharded evaluation
  TpuPolicyEngine - the user-facing facade
"""

from .encoding import ClusterEncoding, PolicyEncoding, encode_cluster, encode_policy
from .api import TpuPolicyEngine, PortCase

__all__ = [
    "ClusterEncoding",
    "PolicyEncoding",
    "encode_cluster",
    "encode_policy",
    "TpuPolicyEngine",
    "PortCase",
]
