"""The TPU engine: compiles a resolved matcher Policy + cluster model into
dense tensors and evaluates the full ingress+egress verdict grid as JAX
kernels (reference counterpart: the sequential loop in
pkg/connectivity/probe/jobrunner.go:68-94 + pkg/matcher/policy.go:131-174).

Pipeline:
  encoding.py  - host-side tensor compiler (numpy): vocab-encode labels,
                 selectors, targets, peers, port specs
  kernel.py    - jit/vmap verdict kernels (single device)
  sharded.py   - Mesh + shard_map source-axis-sharded evaluation
  TpuPolicyEngine - the user-facing facade
"""

import os as _os

_cache_configured = False


def ensure_persistent_compile_cache() -> None:
    """Cache compiled XLA executables across processes: a CLI invocation
    pays 10-20s of TPU compile for the verdict kernels; with the cache a
    repeat run with the same tensor shapes skips it entirely.  Opt out
    with CYCLONUS_JAX_CACHE=0, redirect with CYCLONUS_JAX_CACHE=<dir>.

    Called lazily from the first jax-using engine path (NOT at import
    time - the oracle/native engines never pay the jax import), and
    defers to any cache the user already configured via JAX's own knobs."""
    global _cache_configured
    if _cache_configured:
        return
    _cache_configured = True
    try:
        import jax

        # Full-traceback locations leak CALLER line numbers into the
        # Mosaic custom-call payload, where the cache key's
        # strip-debuginfo pass cannot reach (the payload is an opaque
        # serialized module): editing ANY file on the pallas call stack
        # — even a benchmark script — minted a fresh key for an
        # unchanged kernel and re-paid the 20-40s TPU compile.  Frame-
        # free locations keep the key a function of the program alone.
        # Applied for user-configured caches too (it is key hygiene, not
        # cache placement); CYCLONUS_FULL_LOCATIONS=1 restores the
        # debug-friendly full frames.  Own try: a jax without this flag
        # must not knock out the cache configuration below.
        if _os.environ.get("CYCLONUS_FULL_LOCATIONS", "") != "1":
            try:
                jax.config.update(
                    "jax_include_full_tracebacks_in_locations", False
                )
            except Exception:
                pass

        setting = _os.environ.get("CYCLONUS_JAX_CACHE", "")
        if setting == "0" or _os.environ.get("JAX_COMPILATION_CACHE_DIR"):
            return
        if jax.config.jax_compilation_cache_dir:
            return  # the user configured their own cache; leave it alone
        path = setting or _os.path.join(
            _os.path.expanduser("~"), ".cache", "cyclonus-tpu", "jax"
        )
        _os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # the verdict kernels at CLI-typical cluster sizes compile in
        # ~0.2-1s each; the default 1s floor would cache none of them
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
    except Exception:  # cache is an optimization, never a requirement
        pass


from .encoding import ClusterEncoding, PolicyEncoding, encode_cluster, encode_policy
from .api import TpuPolicyEngine, PortCase

__all__ = [
    "ClusterEncoding",
    "PolicyEncoding",
    "encode_cluster",
    "encode_policy",
    "TpuPolicyEngine",
    "PortCase",
]
