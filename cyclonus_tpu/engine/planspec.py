"""The evaluator dispatch surface as a declarative registry — the
static twin tools/planlint.py lints against and the runtime route
recorder tests/planharness.py replays against.

Three kinds of declaration live here, and all of them are LIVE code,
not documentation:

  * ``PathSpec`` — one evaluator path: its entry point, stage list
    (pre-classify -> pack -> contract -> tier-resolve -> epilogue),
    the flags and ctor args that govern it, its cache-key family, the
    differential gate that pins it to the oracle, the backends it may
    run on, its coverage tier, and the ``when`` feature predicate that
    selects it.  ``predict(entry, features)`` derives the route purely
    from these declarations — the harness asserts actual == predicted.
  * ``Interaction`` — one pairwise feature-compatibility cell: legal /
    fallback / raise, with the fallback target and the exact raise
    message.  engine/api.py's dispatch does not hand-roll these
    decisions anymore: ``resolve_counts_backend`` and
    ``resolve_sharded_counts_kernel`` read the matrix, so a matrix
    edit IS a dispatch change (and tools/planlint.py PL003 fails on a
    dispatch interaction the matrix doesn't declare).
  * ``record(name)`` — the leaf route-recorder call each implementation
    site makes with a LITERAL path name.  tools/planlint.py PL001/PL005
    cross-check the literals against the registry; the runtime recorder
    below replays them under CYCLONUS_PLANHARNESS=1.

Strip contract (same as utils/cachekeys.py): ``ACTIVE`` is read ONCE
at import.  When off — every production run — ``record`` is a
constant-false branch away from a no-op, never syncs, never raises.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Tuple

ACTIVE = os.environ.get("CYCLONUS_PLANHARNESS", "") == "1"

STAGES = ("pre-classify", "pack", "contract", "tier-resolve", "epilogue")

COVERAGE_TIERS = ("tier1", "slow", "device_only")


class PlanError(ValueError):
    """An illegal feature combination, raised with the matrix cell's
    declared message — the SAME exception dispatch raises live."""


@dataclass(frozen=True)
class PathSpec:
    name: str
    entry: str
    stages: Tuple[str, ...]
    flags: Tuple[str, ...] = ()  # governing CYCLONUS_* env flags
    ctor_args: Tuple[str, ...] = ()  # governing TpuPolicyEngine ctor args
    cache_key_family: str = ""  # AOT/jit program family the path compiles under
    gate: str = ""  # differential gate: a tests/ file or a make target
    backends: Tuple[str, ...] = ("cpu", "tpu")
    coverage: str = "tier1"  # tier1 | slow | device_only
    when: Mapping[str, object] = field(default_factory=dict)

    def matches(self, features: Mapping[str, object]) -> bool:
        return all(features.get(k) == v for k, v in self.when.items())


@dataclass(frozen=True)
class Interaction:
    a: str  # feature condition, e.g. "tiers"
    b: str  # feature condition, e.g. "backend=pallas"
    verdict: str  # "legal" | "fallback" | "raise"
    on_explicit: str = ""  # verdict override for an EXPLICIT request
    unless: Tuple[str, ...] = ()  # features exempting the cell (all must hold)
    resolves_to: str = ""  # "feature=value" applied on fallback
    message: str = ""  # the exact raise text (when any verdict is "raise")
    note: str = ""


# --------------------------------------------------------------------------
# The path census.  Entry points are the public dispatch roots on
# TpuPolicyEngine (plus serve's query routing); every leaf reached from
# one of them records exactly one of these names.
# --------------------------------------------------------------------------

PATHS: Tuple[PathSpec, ...] = (
    # --- evaluate_grid -----------------------------------------------------
    PathSpec(
        "grid.dense", "grid",
        stages=("pack", "contract", "tier-resolve", "epilogue"),
        flags=("CYCLONUS_PACK", "CYCLONUS_COMPACT"),
        ctor_args=("tiers",),
        cache_key_family="grid",
        gate="tests/test_engine_parity.py",
        when={"classes": False},
    ),
    PathSpec(
        "grid.classes", "grid",
        stages=("pre-classify", "pack", "contract", "tier-resolve", "epilogue"),
        flags=("CYCLONUS_CLASS_COMPRESS", "CYCLONUS_CLASS_MIN_PODS",
               "CYCLONUS_CIDR_TSS", "CYCLONUS_PACK"),
        ctor_args=("class_compress",),
        cache_key_family="grid_classes",
        gate="tests/test_engine_classes.py",
        when={"classes": True},
    ),
    # --- evaluate_grid_sharded --------------------------------------------
    PathSpec(
        "grid.sharded.ring", "grid_sharded",
        stages=("pack", "contract", "tier-resolve", "epilogue"),
        flags=("CYCLONUS_MESH_SCHEDULE", "CYCLONUS_PACK"),
        cache_key_family="grid_sharded",
        gate="tests/test_engine_sharded.py",
        when={"classes": False, "schedule": "ring"},
    ),
    PathSpec(
        "grid.sharded.allgather", "grid_sharded",
        stages=("pack", "contract", "tier-resolve", "epilogue"),
        flags=("CYCLONUS_MESH_SCHEDULE", "CYCLONUS_PACK"),
        cache_key_family="grid_sharded",
        gate="tests/test_engine_sharded.py",
        when={"classes": False, "schedule": "allgather"},
    ),
    PathSpec(
        "grid.sharded.classes", "grid_sharded",
        stages=("pre-classify", "pack", "contract", "tier-resolve", "epilogue"),
        flags=("CYCLONUS_CLASS_COMPRESS", "CYCLONUS_MESH_SCHEDULE"),
        ctor_args=("class_compress",),
        cache_key_family="grid_sharded_classes",
        gate="tests/test_engine_classes.py",
        when={"classes": True},
    ),
    # --- evaluate_grid_counts ---------------------------------------------
    PathSpec(
        "counts.classes", "counts",
        stages=("pre-classify", "pack", "contract", "epilogue"),
        flags=("CYCLONUS_CLASS_COMPRESS", "CYCLONUS_CLASS_MIN_PODS",
               "CYCLONUS_SLAB_MAX_BYTES", "CYCLONUS_CIDR_TSS"),
        ctor_args=("class_compress",),
        cache_key_family="counts_classes",
        gate="tests/test_engine_classes.py",
        when={"classes": True},
    ),
    PathSpec(
        "counts.pallas", "counts",
        stages=("pack", "contract", "tier-resolve", "epilogue"),
        flags=("CYCLONUS_PACK", "CYCLONUS_PALLAS_DTYPE", "CYCLONUS_PRE_CACHE",
               "CYCLONUS_PALLAS_SLAB", "CYCLONUS_AUTOTUNE"),
        ctor_args=("tiers",),
        cache_key_family="counts_packed",
        gate="tests/test_engine_pallas.py",
        when={"classes": False, "backend": "pallas"},
    ),
    PathSpec(
        "counts.xla", "counts",
        stages=("pack", "contract", "tier-resolve", "epilogue"),
        flags=("CYCLONUS_PACK",),
        ctor_args=("tiers",),
        cache_key_family="counts_tiled",
        gate="tests/test_engine_tiled.py",
        when={"classes": False, "backend": "xla"},
    ),
    # --- steady-state sub-dispatch (within counts.pallas) -------------------
    PathSpec(
        "counts.steady.slab", "counts_steady",
        stages=("contract", "epilogue"),
        flags=("CYCLONUS_PALLAS_SLAB", "CYCLONUS_SLAB_MAX_BYTES",
               "CYCLONUS_AUTOTUNE"),
        cache_key_family="counts_slab",
        gate="tests/test_engine_pallas.py",
        when={"slab": True},
    ),
    PathSpec(
        "counts.steady.packed_tuned", "counts_steady",
        stages=("pack", "contract", "tier-resolve", "epilogue"),
        flags=("CYCLONUS_AUTOTUNE", "CYCLONUS_AUTOTUNE_CACHE",
               "CYCLONUS_AUTOTUNE_TIMEOUT_S"),
        cache_key_family="counts_packed",
        gate="tests/test_engine_packed.py",
        when={"slab": False, "tuned": True},
    ),
    PathSpec(
        "counts.steady.default", "counts_steady",
        stages=("pack", "contract", "tier-resolve", "epilogue"),
        flags=("CYCLONUS_PRE_CACHE",),
        cache_key_family="counts_packed",
        gate="tests/test_engine_pallas.py",
        when={"slab": False, "tuned": False},
    ),
    # --- evaluate_grid_counts_sharded ---------------------------------------
    PathSpec(
        "counts.sharded.classes", "counts_sharded",
        stages=("pre-classify", "pack", "contract", "epilogue"),
        flags=("CYCLONUS_CLASS_COMPRESS", "CYCLONUS_SLAB_MAX_BYTES"),
        ctor_args=("class_compress",),
        cache_key_family="counts_classes_sharded",
        gate="tests/test_engine_classes.py",
        when={"classes": True},
    ),
    PathSpec(
        "counts.sharded.pallas", "counts_sharded",
        stages=("pack", "contract", "epilogue"),
        flags=("CYCLONUS_PACK", "CYCLONUS_PALLAS_DTYPE"),
        cache_key_family="counts_sharded",
        gate="tests/test_engine_sharded.py",
        coverage="device_only",  # interpret-mode pallas under shard_map is
        # exercised only by the TPU multichip suite
        backends=("tpu",),
        when={"classes": False, "kernel": "pallas"},
    ),
    PathSpec(
        "counts.sharded.xla", "counts_sharded",
        stages=("pack", "contract", "tier-resolve", "epilogue"),
        flags=("CYCLONUS_PACK",),
        ctor_args=("tiers",),
        cache_key_family="counts_sharded",
        gate="tests/test_engine_sharded.py",
        when={"classes": False, "kernel": "xla"},
    ),
    # --- ring family ---------------------------------------------------------
    PathSpec(
        "counts.ring", "counts_ring",
        stages=("pack", "contract", "epilogue"),
        flags=("CYCLONUS_PACK",),
        cache_key_family="counts_ring",
        gate="tests/test_engine_tiled.py",
        when={},
    ),
    PathSpec(
        "counts.ring.pipelined", "counts_ring_pipelined",
        stages=("pack", "contract", "epilogue"),
        flags=("CYCLONUS_PACK",),
        cache_key_family="counts_ring",
        gate="tests/test_engine_tiled.py",
        coverage="slow",  # the donation/feed-forward sweep is bench-scale
        when={},
    ),
    PathSpec(
        "counts.ring2d", "counts_ring2d",
        stages=("pack", "contract", "epilogue"),
        flags=("CYCLONUS_PACK",),
        cache_key_family="counts_ring2d",
        gate="tests/test_engine_tiled.py",
        when={},
    ),
    # --- point / streaming / analysis ---------------------------------------
    PathSpec(
        "pairs.aot", "pairs",
        stages=("pack", "contract", "tier-resolve", "epilogue"),
        flags=("CYCLONUS_PACK", "CYCLONUS_AOT_CACHE"),
        ctor_args=("tiers",),
        cache_key_family="pairs",
        gate="tests/test_engine_parity.py",
        when={},
    ),
    PathSpec(
        "grid.blocks", "grid_blocks",
        stages=("pack", "contract", "tier-resolve", "epilogue"),
        flags=("CYCLONUS_PACK",),
        cache_key_family="counts_tiled",
        gate="tests/test_engine_tiled.py",
        when={},
    ),
    PathSpec(
        "firing.raw", "firing",
        stages=("contract", "epilogue"),
        flags=(),
        cache_key_family="firing",
        gate="tests/test_analysis.py",
        when={},
    ),
    # --- serve query routing -------------------------------------------------
    PathSpec(
        "serve.query.live", "serve_query",
        stages=("pack", "contract", "tier-resolve", "epilogue"),
        flags=("CYCLONUS_SERVE_PREWARM", "CYCLONUS_SERVE_PREWARM_PAIRS",
               "CYCLONUS_AOT_CACHE"),
        cache_key_family="pairs",
        gate="tests/test_serve.py",
        when={"warming": False, "shed": False},
    ),
    PathSpec(
        "serve.query.degraded", "serve_query",
        stages=("epilogue",),
        flags=("CYCLONUS_SERVE_PREWARM",),
        cache_key_family="",  # scalar oracle: no compiled program
        gate="tests/test_serve.py",
        when={"warming": True, "shed": False},
    ),
    PathSpec(
        "serve.query.shed", "serve_query",
        stages=("epilogue",),  # typed refusal: no engine work at all
        flags=("CYCLONUS_SLO_ENFORCE",),
        cache_key_family="",  # no compiled program is ever dispatched
        gate="tests/test_slo.py",
        when={"shed": True},
    ),
    # --- audit plane shadow-oracle check ------------------------------------
    PathSpec(
        "serve.audit.check", "serve_audit",
        stages=("epilogue",),  # scalar oracle on the worker thread
        flags=("CYCLONUS_AUDIT", "CYCLONUS_AUDIT_RATE"),
        cache_key_family="",  # host-only: no compiled program
        gate="tests/test_audit.py",
        when={},
    ),
)

REGISTRY: Dict[str, PathSpec] = {p.name: p for p in PATHS}

ENTRIES: Tuple[str, ...] = tuple(sorted({p.entry for p in PATHS}))


# --------------------------------------------------------------------------
# The pairwise compatibility matrix.  Every feature interaction a
# dispatch branch can reach is a cell here; tools/planlint.py PL003
# fails on a reachable interaction the matrix doesn't declare.
# --------------------------------------------------------------------------

INTERACTIONS: Tuple[Interaction, ...] = (
    Interaction(
        "tiers", "backend=pallas", "fallback",
        on_explicit="raise",
        unless=("pack", "packed_tier_ok"),
        resolves_to="backend=xla",
        message=(
            "counts backend 'pallas' cannot evaluate the "
            "precedence-tier lattice on this engine "
            "(packed plan off or tier rows past the fused-"
            "epilogue ceiling); use backend='xla' or "
            "backend=None (auto)"
        ),
        note=(
            "the DENSE pallas counts kernel keeps the networkingv1-only "
            "fast path; under the packed plan the fused tier epilogue "
            "rides pallas unless the rule rows exceed the static-unroll "
            "ceiling"
        ),
    ),
    Interaction(
        "tiers", "kernel=pallas", "fallback",
        on_explicit="raise",
        resolves_to="kernel=xla",
        message=(
            "sharded counts kernel {kernel!r} cannot evaluate "
            "the precedence-tier lattice; use kernel='xla' or "
            "kernel=None (auto) on a tiered engine"
        ),
        note=(
            "per-device pallas keeps the networkingv1 fast path; the "
            "XLA tile body carries the tier resolution epilogue"
        ),
    ),
    Interaction(
        "classes", "backend=pallas", "legal",
        note=(
            "the compressed route takes priority over the backend pick "
            "(identical counts either way; the class grid is small "
            "enough that the XLA tile loop is already device-bound)"
        ),
    ),
    Interaction(
        "classes", "backend=xla", "legal",
        note="compressed route priority, same as the pallas cell",
    ),
    Interaction(
        "classes", "over_budget", "fallback",
        resolves_to="classes=False",
        note=(
            "_class_counts_eligible: aux/index tensors + class "
            "precompute past CYCLONUS_SLAB_MAX_BYTES decline the "
            "compressed route and fall back to the dense kernels"
        ),
    ),
    Interaction(
        "classes", "tiers", "legal",
        note=(
            "class signatures include the tier rule rows; the class "
            "grid carries the tier-resolve epilogue (test_tiers.py "
            "pins tiered-vs-oracle parity under forced compression)"
        ),
    ),
    Interaction(
        "classes", "schedule=ring", "legal",
        note="grid.sharded.classes shards the class axis; the schedule "
             "passes through",
    ),
    Interaction(
        "pack", "slab", "fallback",
        resolves_to="slab=False",
        note=(
            "_slab_plan: the slab path (and its multi-second host "
            "window pass) is retired under the packed dtype plan — the "
            "packed kernel's word contraction is a deeper depth cut "
            "from the same precompute; CYCLONUS_PACK=0 restores it"
        ),
    ),
    Interaction(
        "slab=auto", "pre_cache=0", "fallback",
        resolves_to="slab=False",
        note=(
            "_slab_plan: the autotune point IS the first steady-state "
            "(pinned precompute) call; with the pre-cache off it never "
            "fires, so auto never pays the slab plan for a dead path"
        ),
    ),
    Interaction(
        "warming", "query", "fallback",
        resolves_to="route=serve.query.degraded",
        note=(
            "queries during serve prewarm answer from the scalar-oracle "
            "fallback — exact at host speed, counted in "
            "cyclonus_tpu_serve_degraded_queries_total"
        ),
    ),
    Interaction(
        "slo=exhausted", "query", "fallback",
        resolves_to="route=serve.query.shed",
        note=(
            "query_p99 error budget exhausted (CYCLONUS_SLO_ENFORCE): "
            "queries get a typed Shed refusal — never a wrong verdict; "
            "the refusal carries shed=True plus an error so the "
            "all-False allow bits cannot be misread as deny"
        ),
    ),
    Interaction(
        "slo=burning", "query", "fallback",
        resolves_to="route=serve.query.degraded",
        note=(
            "query_p99 budget burning routes queries onto the same "
            "scalar-oracle path warming uses — exact answers at host "
            "speed while device load drains; hysteresis "
            "(CYCLONUS_SLO_EXIT_BURN + CYCLONUS_SLO_HOLD_S) keeps the "
            "route from flapping"
        ),
    ),
)

_INTER_INDEX: Dict[Tuple[str, str], Interaction] = {
    (i.a, i.b): i for i in INTERACTIONS
}


def interaction(a: str, b: str) -> Interaction:
    """The declared cell for (a, b), order-insensitive."""
    it = _INTER_INDEX.get((a, b)) or _INTER_INDEX.get((b, a))
    if it is None:
        raise KeyError(f"no declared interaction for ({a!r}, {b!r})")
    return it


# --------------------------------------------------------------------------
# Live resolvers — engine/api.py dispatch calls these, so the matrix
# above IS the dispatch logic for the cells it declares.
# --------------------------------------------------------------------------

def resolve_counts_backend(
    *,
    backend: str,
    explicit: bool,
    tiers: bool,
    pack: bool,
    packed_tier_ok,
) -> str:
    """evaluate_grid_counts's tiers x pallas decision, read off the
    matrix: exempt (legal) when the packed plan fuses the tier
    epilogue, else fallback on auto / raise on an explicit request.
    `packed_tier_ok` is a zero-arg callable — the eligibility scan is
    only paid when the cell is actually consulted."""
    if not (tiers and backend == "pallas"):
        return backend
    it = interaction("tiers", "backend=pallas")
    if pack and packed_tier_ok():
        return backend  # it.unless: ("pack", "packed_tier_ok")
    verdict = it.on_explicit if explicit and it.on_explicit else it.verdict
    if verdict == "raise":
        raise PlanError(it.message)
    return it.resolves_to.split("=", 1)[1]


def resolve_sharded_counts_kernel(
    *, kernel: Optional[str], tiers: bool
) -> Optional[str]:
    """evaluate_grid_counts_sharded's tiers x pallas decision off the
    matrix.  None (auto) under tiers resolves to the XLA tile body; an
    explicit non-xla kernel raises with the declared message."""
    if not tiers or kernel == "xla":
        return kernel
    it = interaction("tiers", "kernel=pallas")
    verdict = it.on_explicit if kernel is not None and it.on_explicit else it.verdict
    if verdict == "raise":
        raise PlanError(it.message.format(kernel=kernel))
    return it.resolves_to.split("=", 1)[1]


# --------------------------------------------------------------------------
# Static route prediction — the harness's twin of the live dispatch.
# Derives the route purely from PATHS + INTERACTIONS; it never touches
# an engine.
# --------------------------------------------------------------------------

def predict(entry: str, features: Mapping[str, object]) -> str:
    """The path `entry` routes to under `features` (raw, pre-resolution
    flags), per the declarations alone.  Raises PlanError exactly where
    the live dispatch raises."""
    f = dict(features)
    f.setdefault("classes", False)
    if entry == "counts":
        backend = f.get("backend")
        explicit = backend is not None
        if backend is None:
            backend = "pallas" if f.get("platform") == "tpu" else "xla"
        # the live dispatch consults the tiers cell BEFORE the classes
        # short-circuit: an explicit pallas request on a tiered engine
        # raises even when the compressed route would have absorbed it
        backend = resolve_counts_backend(
            backend=backend,
            explicit=explicit,
            tiers=bool(f.get("tiers", False)),
            pack=bool(f.get("pack", False)),
            packed_tier_ok=lambda: bool(f.get("packed_tier_ok", False)),
        )
        f["backend"] = backend
    elif entry == "counts_sharded":
        if not f.get("classes", False):
            kernel = resolve_sharded_counts_kernel(
                kernel=f.get("kernel"), tiers=bool(f.get("tiers", False))
            )
            if kernel is None:
                kernel = "pallas" if f.get("platform") == "tpu" else "xla"
            f["kernel"] = kernel
    elif entry == "grid_sharded":
        f.setdefault("schedule", "ring")
    elif entry == "counts_steady":
        # pack retires the slab path before the steady dispatch ever
        # sees it (the pack x slab matrix cell)
        if f.get("pack", False):
            f["slab"] = False
        f.setdefault("slab", False)
        f.setdefault("tuned", False)
    elif entry == "serve_query":
        f.setdefault("warming", False)
        f.setdefault("shed", False)
    candidates = [
        p for p in PATHS if p.entry == entry and p.matches(f)
    ]
    if not candidates:
        raise PlanError(f"no declared path for entry {entry!r} under {f!r}")
    # most specific `when` wins (counts.classes over the backend pair)
    candidates.sort(key=lambda p: (-len(p.when), p.name))
    if len(candidates) > 1 and len(candidates[0].when) == len(candidates[1].when):
        raise PlanError(
            f"ambiguous route for entry {entry!r} under {f!r}: "
            f"{[p.name for p in candidates[:2]]}"
        )
    return candidates[0].name


# --------------------------------------------------------------------------
# The runtime route recorder (armed by CYCLONUS_PLANHARNESS=1, read
# once at import — the strip contract).
# --------------------------------------------------------------------------

_LOCK = threading.Lock()
_ROUTES: List[str] = []
_DROPPED = 0


def _count_dropped() -> None:
    global _DROPPED
    _DROPPED += 1


def dropped() -> int:
    """Routes the recorder failed to append (harness debugging aid; 0
    in any healthy run)."""
    return _DROPPED


def record(name: str) -> None:  # never-raises
    """Leaf route-recorder call.  Callers pass a LITERAL path name —
    tools/planlint.py extracts these literals to cross-check against
    the registry (PL001: undeclared literal; PL005: declared path no
    leaf records).  No-op unless the harness armed the recorder."""
    if not ACTIVE:
        return
    try:
        with _LOCK:
            _ROUTES.append(name)
    except Exception:
        _count_dropped()


def drain() -> List[str]:
    """Recorded routes since the last drain, in dispatch order.  Empty
    when the recorder is off."""
    if not ACTIVE:
        return []
    with _LOCK:
        out = list(_ROUTES)
        _ROUTES.clear()
    return out


def manifest() -> Dict:
    """The plan manifest: the registry + matrix as plain data — what
    tools/planlint.py emits to artifacts/plan_manifest.json and the
    schema test pins."""
    return {
        "version": 1,
        "entries": list(ENTRIES),
        "stages": list(STAGES),
        "paths": [
            {
                "name": p.name,
                "entry": p.entry,
                "stages": list(p.stages),
                "flags": list(p.flags),
                "ctor_args": list(p.ctor_args),
                "cache_key_family": p.cache_key_family,
                "gate": p.gate,
                "backends": list(p.backends),
                "coverage": p.coverage,
                "when": dict(p.when),
            }
            for p in PATHS
        ],
        "interactions": [
            {
                "a": i.a,
                "b": i.b,
                "verdict": i.verdict,
                "on_explicit": i.on_explicit,
                "unless": list(i.unless),
                "resolves_to": i.resolves_to,
                "message": i.message,
                "note": i.note,
            }
            for i in INTERACTIONS
        ],
    }
